"""Hybrid logical clocks: causal cross-host ordering under clock skew.

Every fleet artifact this repo folds — flight-recorder events, reqtrace
spans, the request/store journals, heartbeat leases — is stamped with a
wall-clock ``t`` by its writer. That is fine per host and wrong across
hosts: a router whose clock runs 2 s ahead journals its fence *before*
the SIGKILL it reacted to, and the post-mortem timeline reads backwards.
An HLC (Kulkarni et al., "Logical Physical Clocks") fixes exactly this:
each timestamp is a ``(wall_us, counter)`` pair where the wall component
never moves backwards (a stepped-back OS clock just stops advancing it)
and the counter breaks ties, so happened-before edges that the system
actually observes — a record read is a message received — are preserved
in timestamp order while staying within bounded skew of real time.

Merges ride existing read paths, no new RPC: the lease registry merges
the HLC carried in every lease value it sweeps, and the journal/store
fold loops merge each record they read. A reader's next stamp therefore
sorts after everything it has observed, on every host.

Encoding: ``"{wall_us:016x}.{counter:08x}"`` — fixed-width hex, so the
*string* sort order equals the numeric order and JSONL consumers (sort,
awk, the timeline CLI) can order records without parsing. Readers must
treat a missing/empty ``hlc`` field as "before all stamped records"
(pre-upgrade journals remain foldable).

Thread safety: one lock per clock; the module singleton is shared by
every recorder in the process, which is what makes a process's own
stamps totally ordered.
"""

import threading
import time
from typing import Callable, Optional, Tuple

__all__ = ["HLC", "clock", "tick", "merge", "observe", "unpack", "pack",
           "reset", "ZERO"]

# Sorts before every real stamp; what readers substitute for a missing
# ``hlc`` field on pre-upgrade records.
ZERO = "0" * 16 + "." + "0" * 8


def pack(wall_us: int, counter: int) -> str:
    """Fixed-width hex encoding whose lexicographic order IS the HLC
    order (wall first, counter tie-break)."""
    return f"{wall_us:016x}.{counter:08x}"


def unpack(stamp: Optional[str]) -> Tuple[int, int]:
    """Decode a packed stamp; garbage or missing stamps decode to
    (0, 0) — "before everything", never a crash (fold tolerance)."""
    if not stamp or not isinstance(stamp, str):
        return (0, 0)
    try:
        wall_hex, _, c_hex = stamp.partition(".")
        return (int(wall_hex, 16), int(c_hex, 16)) if c_hex else (0, 0)
    except ValueError:
        return (0, 0)


class HLC:
    """One hybrid logical clock. ``physical`` is injectable (seconds,
    ``time.time`` signature) so tests can step it backwards."""

    def __init__(self, physical: Callable[[], float] = time.time):
        self.physical = physical
        self._wall_us = 0
        self._counter = 0
        self._lock = threading.Lock()

    def _now_us(self) -> int:
        return int(self.physical() * 1e6)

    def tick(self) -> str:
        """Stamp a local/send event. Monotonic even when the physical
        clock steps backwards: the wall component only ratchets up, the
        counter absorbs same-microsecond (or rewound-clock) bursts."""
        pt = self._now_us()
        with self._lock:
            if pt > self._wall_us:
                self._wall_us, self._counter = pt, 0
            else:
                self._counter += 1
            return pack(self._wall_us, self._counter)

    def merge(self, remote: Optional[str]) -> str:
        """Stamp a receive event: advance past ``remote`` (a packed stamp
        read off a lease value / journal record) AND local time. After
        this, every local tick() sorts after the merged stamp."""
        r_wall, r_counter = unpack(remote)
        pt = self._now_us()
        with self._lock:
            if pt > self._wall_us and pt > r_wall:
                self._wall_us, self._counter = pt, 0
            elif self._wall_us == r_wall:
                self._counter = max(self._counter, r_counter) + 1
            elif self._wall_us > r_wall:
                self._counter += 1
            else:
                self._wall_us, self._counter = r_wall, r_counter + 1
            return pack(self._wall_us, self._counter)

    def observe(self, remote: Optional[str]) -> None:
        """Merge without minting a stamp (fold loops call this per
        record; only the next actual event needs a fresh stamp)."""
        r_wall, r_counter = unpack(remote)
        with self._lock:
            if (r_wall, r_counter) > (self._wall_us, self._counter):
                self._wall_us, self._counter = r_wall, r_counter

    def read(self) -> str:
        """Current stamp without advancing (diagnostics only)."""
        with self._lock:
            return pack(self._wall_us, self._counter)


# --------------------------------------------------------- module singleton
# Shared by events.py, reqtrace.py, journal.py, kvstore.py and lease.py in
# this process: one clock per process means a process's stamps are totally
# ordered regardless of which recorder emitted them.
_CLOCK = HLC()


def clock() -> HLC:
    return _CLOCK


def tick() -> str:
    return _CLOCK.tick()


def merge(remote: Optional[str]) -> str:
    return _CLOCK.merge(remote)


def observe(remote: Optional[str]) -> None:
    _CLOCK.observe(remote)


def reset(physical: Callable[[], float] = time.time) -> None:
    """Swap the process clock (tests only — injects a fake physical
    clock and zeroes the logical state)."""
    global _CLOCK
    _CLOCK = HLC(physical)
