"""Continuous train->serve deployment loop.

``publish.py`` is the trainer side: after each integrity-manifest commit
it atomically updates a ``published.json`` pointer next to the Orbax
root. ``reload.py`` is the serving side: a watcher polls the pointer,
verifies the manifest BEFORE load, and hot-swaps the engine's weights in
a prefill-pause without dropping in-flight requests.
"""

from .publish import (  # noqa: F401
    POINTER_NAME,
    Pointer,
    Publisher,
    manifest_digest,
    read_pointer,
    verify_pointer,
    write_pointer,
)
from .reload import HotReloader, PointerWatcher  # noqa: F401
