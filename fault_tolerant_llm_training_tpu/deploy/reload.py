"""Watcher + verified zero-downtime hot weight reload for serving.

:class:`PointerWatcher` polls the trainer's ``published.json``
(deploy/publish.py) and offers each distinct publish exactly once.
:class:`HotReloader` then runs the swap state machine::

    VERIFY  -> pointer digest + per-file CRC check of the published step
               (and its draft sub-pointer) BEFORE anything is loaded; a
               failing publish is rejected + audited, serving continues
               on current weights
    PAUSE   -> admission closes (Scheduler.stop_admission); the reload is
               driven between decode iterations, so the in-flight round
               has already finished — no request is dropped, no slot
               freed
    RESTORE -> params restored through the same cross-topology path as
               startup (engine.restore_params) onto the engine's mesh; a
               restore that silently fell back to an OLDER step
               (checkpoint/manager.py _verified_step) is treated as a
               rejection — the pointer names ONE step, serving never
               downgrades implicitly. When the pointer carries a
               ``weights`` sub-entry (quantize-at-publish,
               deploy/publish.py), the CRC-verified int8 artifact is
               loaded and dequantized instead — serving never reads the
               full-precision checkpoint at all
    SWAP    -> engine.reload_params installs the new arrays into the
               running AOT programs (no re-compile — the programs take
               params per call, only the cache is donated); draft params
               swap in the same pause; the prefix cache is flushed (its
               cached KV was computed with the OLD weights)
    RESUME  -> admission reopens; the swap is audited
               (AUDIT_RELOAD_FMT) with counter + step gauge +
               swap-latency histogram on /metrics

In-flight requests keep their already-computed KV: from the swap on,
their decode runs new weights over old-KV context (the standard
continuous-batching reload semantics — finishing a started stream beats
dropping it). Requests ADMITTED after the swap run prefill + decode
entirely under the new weights, so their streams bit-match a fresh serve
of the published step — the property the chaos campaign pins.
"""

import os
import time
from typing import Optional

from ..ft.retry import RetryDeadlineExceeded, retry_with_backoff
from ..obs import events
from ..obs.registry import REGISTRY
from ..utils.logging import (
    AUDIT_ADAPTER_FMT,
    AUDIT_RELOAD_FMT,
    AUDIT_RELOAD_REJECTED_FMT,
    logger,
)
from .publish import (
    Pointer,
    load_weights_artifact,
    read_pointer_strict,
    verify_pointer,
)

_M_RELOADS = REGISTRY.counter(
    "ftl_weights_reload_total",
    "Hot weight swaps completed by the serving process")
_M_WEIGHTS_BYTES = REGISTRY.gauge(
    "weights_artifact_bytes",
    "Payload bytes of the quantized weights artifact currently serving "
    "(0 when weights came from a full-precision checkpoint restore)")
_M_REJECTED = REGISTRY.counter(
    "ftl_weights_reload_rejected_total",
    "Published checkpoints rejected by verify-before-load")
_M_STEP = REGISTRY.gauge(
    "ftl_weights_step",
    "Checkpoint step of the weights currently being served")
_M_SWAP = REGISTRY.histogram(
    "ftl_weights_swap_seconds",
    "Wall time of one hot weight swap (verify + restore + install)")


class PointerWatcher:
    """Offer each distinct publish of ``published.json`` exactly once.

    Distinctness is the (job_id, step, digest) triple, so a republish of
    the same step with a rewritten manifest is a NEW offer, while a
    rejected publish is not re-verified on every poll — the trainer must
    publish something new to be considered again.

    Transient pointer-read failures (a slow or flapping filesystem, a
    mid-replace window) are retried with a bounded deadline
    (ft/retry.py, the same policy as the fleet lease path): on expiry the
    poll renders a clean "no pointer this poll" verdict — a dead
    coordinator costs at most ``deadline_seconds`` per poll, never a hang
    and never a crashed serving process.
    """

    def __init__(self, root: str, deadline_seconds: float = 1.0,
                 clock=time.monotonic, sleep=time.sleep):
        self.root = os.path.abspath(root)
        self.deadline = float(deadline_seconds)
        self.clock = clock
        self.sleep = sleep
        self._seen = None

    def poll(self) -> Optional[Pointer]:
        try:
            ptr = retry_with_backoff(
                lambda: read_pointer_strict(self.root),
                deadline_seconds=self.deadline,
                retry_on=(OSError, ValueError, KeyError, TypeError),
                clock=self.clock, sleep=self.sleep,
                what="published.json read")
        except RetryDeadlineExceeded as e:
            logger.warning(f"[DEPLOY] pointer poll gave up: {e}")
            return None
        if ptr is None:
            return None
        key = (ptr.job_id, ptr.step, ptr.manifest_digest)
        if key == self._seen:
            return None
        self._seen = key
        return ptr


class HotReloader:
    """Swap serving weights to a verified published checkpoint in a
    prefill-pause (module docstring has the state machine)."""

    def __init__(self, engine, scheduler, cfg, checkpoint_path: str,
                 draft_cfg=None, adaptive_k=None, chaos=None,
                 clock=time.monotonic):
        self.engine = engine
        self.scheduler = scheduler
        self.cfg = cfg
        self.draft_cfg = draft_cfg
        self.root = os.path.abspath(checkpoint_path)
        self.adaptive_k = adaptive_k
        self.chaos = chaos
        self.clock = clock
        self.reloads = 0
        self.rejects = 0
        current = getattr(engine, "restored_step", None)
        if current is not None:
            _M_STEP.set(int(current))

    def _reject(self, ptr: Pointer, detail: str, current) -> None:
        self.rejects += 1
        _M_REJECTED.inc()
        events.emit_audit(
            logger,
            AUDIT_RELOAD_REJECTED_FMT.format(step=ptr.step, detail=detail,
                                             current=current),
            "weights_reload_rejected", step=int(ptr.step), detail=detail,
            current=current)
        events.flush()

    def maybe_reload(self, ptr: Optional[Pointer]) -> bool:
        """Verify + swap to ``ptr``; returns True iff the swap completed.
        Must be called between scheduler.step() iterations (the serve
        loop's cadence) so no decode round is in flight."""
        if ptr is None:
            return False
        from ..inference.engine import restore_params
        from ..models.llama import unstack_layer_params

        current = getattr(self.engine, "restored_step", None)
        ok, detail = verify_pointer(self.root, ptr)
        if not ok:
            self._reject(ptr, detail, current)
            return False
        t0 = self.clock()
        was_open = self.scheduler.admission_open
        self.scheduler.stop_admission()
        art_bytes = 0
        try:
            if ptr.weights is not None:
                # quantize-at-publish path: the verified artifact IS the
                # weights — dequantized back to checkpoint dtype, the
                # full-precision checkpoint is never read by serving
                if int(ptr.weights.get("step", -1)) != ptr.step:
                    self._reject(
                        ptr, "weights sub-pointer names step "
                             f"{ptr.weights.get('step')}, pointer names "
                             f"{ptr.step}", current)
                    return False
                params = load_weights_artifact(self.root, ptr.weights)
                art_bytes = int(ptr.weights.get("nbytes", 0))
            else:
                params, got = restore_params(
                    self.root, ptr.job_id, self.cfg, step=ptr.step,
                    mesh=getattr(self.engine, "mesh", None))
                if got != ptr.step:
                    self._reject(ptr, f"restore fell back to step {got}",
                                 current)
                    return False
            if self.cfg.layer_impl == "scan":
                # the engine converted to loop form at build; mirror it
                params = unstack_layer_params(params, self.cfg.n_layers)
            draft_params = None
            if ptr.draft is not None and getattr(self.engine, "spec_k", 0):
                if self.draft_cfg is None:
                    self._reject(ptr, "pointer carries a draft but serving "
                                      "was built without one", current)
                    return False
                draft_params, dgot = restore_params(
                    self.root, str(ptr.draft["job_id"]), self.draft_cfg,
                    step=int(ptr.draft["step"]),
                    mesh=getattr(self.engine, "mesh", None))
                if dgot != int(ptr.draft["step"]):
                    self._reject(ptr, f"draft restore fell back to step "
                                      f"{dgot}", current)
                    return False
                if self.draft_cfg.layer_impl == "scan":
                    draft_params = unstack_layer_params(
                        draft_params, self.draft_cfg.n_layers)
            if self.chaos is not None:
                # mid-swap fault window: new params restored but not yet
                # installed — a reload_signal lands here
                self.chaos.on_reload(self.reloads + 1)
            self.engine.reload_params(params)
            adapters_swapped = 0
            if ptr.adapters:
                # Tenant adapter hot-swap, in the SAME pause and equally
                # recompile-free (the programs take the adapter pool per
                # call): each verified sub-pointer registers its artifact
                # and, when that adapter is resident, pages the new
                # version in ALONGSIDE the old one — in-flight slots keep
                # decoding the version they pinned until they drain
                # (adapters.py swap/release). A pool too full to hold
                # both versions defers THAT adapter (old keeps serving);
                # it never rejects the weights swap.
                mgr = getattr(self.engine, "adapters", None)
                if mgr is None:
                    logger.warning(
                        "[DEPLOY] pointer carries %d adapter sub-"
                        "pointer(s) but serving was built without "
                        "adapter serving (adapter_rank=0); ignoring",
                        len(ptr.adapters))
                else:
                    for name, sub in sorted(ptr.adapters.items()):
                        art_dir = os.path.join(self.root,
                                               str(sub["path"]))
                        if mgr.swap(name, art_dir):
                            adapters_swapped += 1
                            events.emit_audit(
                                logger, AUDIT_ADAPTER_FMT.format(
                                    action="swap", name=name,
                                    pages=mgr.layout.pages_per_adapter,
                                    detail=f"step {sub.get('step', 0)} "
                                           f"in-flight slots preserved"),
                                "adapter", name=name,
                                step=int(sub.get("step", 0)))
                        else:
                            logger.warning(
                                "[DEPLOY] adapter %s swap deferred: the "
                                "adapter pool cannot hold the new "
                                "version alongside the in-flight one",
                                name)
            if draft_params is not None:
                self.engine.reload_draft_params(draft_params)
                if self.adaptive_k is not None:
                    # a fresh draft resets the acceptance estimate: start
                    # optimistic again instead of dragging the stale
                    # draft's learned-down k into the new regime
                    self.adaptive_k.reset()
            if getattr(self.scheduler, "prefix_cache", None) is not None:
                self.scheduler.prefix_cache.flush()
            self.engine.restored_step = ptr.step
            self.reloads += 1
        except Exception as e:  # a verified step should restore; if the
            # filesystem disagrees mid-read, reject and keep serving
            self._reject(ptr, f"restore failed ({e})", current)
            return False
        finally:
            if was_open:
                self.scheduler.resume_admission()
        dt = self.clock() - t0
        _M_RELOADS.inc()
        _M_STEP.set(int(ptr.step))
        _M_SWAP.observe(dt)
        _M_WEIGHTS_BYTES.set(art_bytes)
        events.emit_audit(
            logger,
            AUDIT_RELOAD_FMT.format(old=current, new=ptr.step,
                                    active=len(self.scheduler.active),
                                    ms=dt * 1e3),
            "weights_reload", step=int(ptr.step), old=current, dur=dt,
            active=len(self.scheduler.active), draft=bool(ptr.draft),
            weights=bool(ptr.weights), artifact_bytes=art_bytes,
            adapters=adapters_swapped)
        events.flush()
        return True
