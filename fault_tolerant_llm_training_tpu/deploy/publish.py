"""Verified checkpoint publishing: the trainer's half of the deploy loop.

A publish is ONE atomic pointer write: ``published.json`` next to the
Orbax checkpoint root names the step directory serving should load, plus
the sha256 of that step's ``integrity.json`` — so the serving watcher can
prove the manifest it verifies against is the manifest that was published,
not a later rewrite. The pointer write follows the same atomic
tmp + fsync + rename + dir-fsync discipline as the manifest itself
(checkpoint/manager.py): a reader never observes a torn pointer, and a
crash mid-publish leaves the previous pointer intact.

The pointer optionally carries a ``draft`` sub-pointer (same fields) so a
speculative-decoding deployment can refresh target and draft weights in
the same serving-side swap.

``python -m fault_tolerant_llm_training_tpu.deploy.publish`` republishes
any manifested step by hand — the campaign driver uses it to stage
rollbacks and chaos-corrupted publishes.
"""

import argparse
import dataclasses
import hashlib
import json
import os
import sys
from typing import Optional, Tuple

from ..checkpoint.manager import MANIFEST_NAME, _fsync_dir, verify_step_dir
from ..obs import events
from ..obs.registry import REGISTRY
from ..utils.logging import AUDIT_PUBLISH_FMT, init_logger, logger

POINTER_NAME = "published.json"

_M_PUBLISHED = REGISTRY.counter(
    "ftl_publish_total",
    "Checkpoint pointer publishes committed by this process")
_M_PUBLISHED_STEP = REGISTRY.gauge(
    "ftl_published_step",
    "Step of the most recently published checkpoint pointer")


@dataclasses.dataclass
class Pointer:
    """One published checkpoint: what serving should load and how to
    verify it. ``path`` is the step directory relative to the checkpoint
    root (the directory holding ``published.json``); ``draft`` is an
    optional dict with the same ``step``/``job_id``/``path``/
    ``manifest_digest`` keys for the speculative draft model."""

    step: int
    job_id: str
    path: str
    manifest_digest: str
    draft: Optional[dict] = None
    version: int = 1


def pointer_path(root: str) -> str:
    return os.path.join(os.path.abspath(root), POINTER_NAME)


def manifest_digest(step_dir: str) -> Optional[str]:
    """sha256 hex of the step's ``integrity.json`` bytes (None if the step
    has no manifest — such a step is not publishable: the watcher could
    not verify what it loads)."""
    path = os.path.join(step_dir, MANIFEST_NAME)
    if not os.path.isfile(path):
        return None
    with open(path, "rb") as fh:
        return hashlib.sha256(fh.read()).hexdigest()


def write_pointer(root: str, ptr: Pointer) -> str:
    """Atomic pointer commit, same discipline as ``write_manifest``."""
    final = pointer_path(root)
    tmp = final + ".tmp"
    with open(tmp, "w") as fh:
        json.dump(dataclasses.asdict(ptr), fh)
        fh.flush()
        os.fsync(fh.fileno())
    os.replace(tmp, final)
    _fsync_dir(os.path.dirname(final))
    return final


def read_pointer_strict(root: str) -> Optional[Pointer]:
    """Read ``published.json``: a genuinely ABSENT pointer is None, but a
    torn, unreadable or wrong-shaped one RAISES — the distinction the
    watcher's bounded retry (ft/retry.py) needs to tell "nothing published
    yet" from "transient I/O trouble worth retrying"."""
    try:
        with open(pointer_path(root)) as fh:
            data = json.load(fh)
    except FileNotFoundError:
        return None
    return Pointer(step=int(data["step"]), job_id=str(data["job_id"]),
                   path=str(data["path"]),
                   manifest_digest=str(data["manifest_digest"]),
                   draft=data.get("draft"),
                   version=int(data.get("version", 1)))


def read_pointer(root: str) -> Optional[Pointer]:
    """Read ``published.json`` tolerantly: a missing, torn, or
    wrong-shaped pointer reads as None (the watcher just polls again) —
    the atomic write makes torn reads near-impossible, but a reader must
    never crash the serving process over a pointer file."""
    try:
        return read_pointer_strict(root)
    except (OSError, ValueError, KeyError, TypeError):
        return None


def _verify_target(root: str, path: str, digest: str) -> Tuple[bool, str]:
    step_dir = os.path.join(os.path.abspath(root), path)
    actual = manifest_digest(step_dir)
    if actual is None:
        return False, f"published step has no integrity manifest ({path})"
    if actual != digest:
        return False, (f"manifest digest mismatch ({path}): the published "
                       f"manifest was replaced after publish")
    ok, detail = verify_step_dir(step_dir)
    if not ok:
        return False, f"integrity check failed ({path}): {detail}"
    return True, "ok"


def verify_pointer(root: str, ptr: Pointer) -> Tuple[bool, str]:
    """Verify-before-load: the published step's manifest must be the one
    that was published (sha256) AND every manifest-listed file must pass
    its size/CRC check — for the draft sub-pointer too, when present.
    Returns ``(ok, detail)``."""
    ok, detail = _verify_target(root, ptr.path, ptr.manifest_digest)
    if not ok:
        return ok, detail
    if ptr.draft is not None:
        try:
            ok, detail = _verify_target(root, str(ptr.draft["path"]),
                                        str(ptr.draft["manifest_digest"]))
        except (KeyError, TypeError):
            return False, "malformed draft sub-pointer"
        if not ok:
            return False, f"draft {detail}"
    return True, "ok"


def newest_manifested_step(root: str, job_id: str) -> Optional[int]:
    """Newest finalized step of ``checkpoint_{job_id}`` that carries an
    integrity manifest (the publishable set)."""
    d = os.path.join(os.path.abspath(root), f"checkpoint_{job_id}")
    if not os.path.isdir(d):
        return None
    steps = sorted((int(n) for n in os.listdir(d) if n.isdigit()),
                   reverse=True)
    for step in steps:
        if manifest_digest(os.path.join(d, str(step))) is not None:
            return step
    return None


class Publisher:
    """Atomically points serving at a verified checkpoint step.

    The trainer calls :meth:`publish` after each periodic save's
    integrity sweep (training/loop.py, host 0 only); the CLI below drives
    the same path by hand. A chaos injector hooks the moment AFTER the
    pointer commit (``publish_corrupt``) so campaigns can prove the
    serving watcher rejects a corrupted publish.
    """

    def __init__(self, checkpoint_path: str, job_id: str, chaos=None):
        self.root = os.path.abspath(checkpoint_path)
        self.job_id = str(job_id)
        self.chaos = chaos

    def step_dir(self, step: int, job_id: Optional[str] = None) -> str:
        return os.path.join(self.root, f"checkpoint_{job_id or self.job_id}",
                            str(step))

    def publish(self, step: int,
                draft: Optional[dict] = None) -> Optional[Pointer]:
        """Publish ``step`` (which must carry an integrity manifest);
        returns the committed pointer, or None if the step is not
        publishable. ``draft`` is an optional pre-built draft sub-pointer
        dict (see :func:`draft_pointer`)."""
        step_dir = self.step_dir(step)
        digest = manifest_digest(step_dir)
        if digest is None:
            logger.warning(
                f"[DEPLOY] step {step} has no integrity manifest under "
                f"{step_dir}; not publishing")
            return None
        ptr = Pointer(step=int(step), job_id=self.job_id,
                      path=os.path.relpath(step_dir, self.root),
                      manifest_digest=digest, draft=draft)
        write_pointer(self.root, ptr)
        _M_PUBLISHED.inc()
        _M_PUBLISHED_STEP.set(int(step))
        events.emit_audit(
            logger,
            AUDIT_PUBLISH_FMT.format(step=int(step), digest=digest[:12]),
            "publish", step=int(step), digest=digest, path=ptr.path,
            draft=bool(draft))
        events.flush()
        if self.chaos is not None:
            # post-commit corruption window: the pointer is live, the
            # files it names get flipped — exactly what verify-before-load
            # exists to catch
            self.chaos.on_publish(step_dir, int(step), logger)
        return ptr

    def draft_pointer(self, job_id: str,
                      step: Optional[int] = None) -> Optional[dict]:
        """Build a draft sub-pointer for a draft trained into the same
        checkpoint root (its own ``checkpoint_{job_id}``)."""
        if step is None:
            step = newest_manifested_step(self.root, job_id)
            if step is None:
                return None
        step_dir = self.step_dir(step, job_id=job_id)
        digest = manifest_digest(step_dir)
        if digest is None:
            return None
        return {"step": int(step), "job_id": str(job_id),
                "path": os.path.relpath(step_dir, self.root),
                "manifest_digest": digest}


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="fault_tolerant_llm_training_tpu.deploy.publish",
        description="(Re)publish a checkpoint step to published.json — "
                    "the pointer serving's hot-reload watcher follows.")
    p.add_argument("--checkpoint-path", required=True,
                   help="directory passed to training's --checkpoint-path")
    p.add_argument("--job-id", required=True,
                   help="job id the checkpoint was written under")
    p.add_argument("--step", type=int, default=None,
                   help="step to publish (default: newest manifested)")
    p.add_argument("--draft-job-id", default="",
                   help="also publish a draft sub-pointer from this job's "
                        "checkpoints (same checkpoint root)")
    p.add_argument("--draft-step", type=int, default=None,
                   help="draft step (default: newest manifested)")
    p.add_argument("--chaos", default="",
                   help="fault schedule keyed by the published step "
                        "(publish_corrupt only)")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--event-log", default="",
                   help="flight-recorder JSONL path ('' = disabled)")
    args = p.parse_args(argv)

    init_logger()
    if args.event_log:
        events.configure(args.event_log, job="publish", host=os.getpid())
    chaos = None
    if args.chaos:
        from ..chaos import ChaosInjector, parse_schedule

        chaos = ChaosInjector(
            parse_schedule(args.chaos, allowed=("publish_corrupt",)),
            seed=args.seed)
    pub = Publisher(args.checkpoint_path, args.job_id, chaos=chaos)
    step = args.step
    if step is None:
        step = newest_manifested_step(args.checkpoint_path, args.job_id)
        if step is None:
            logger.error("[DEPLOY] no manifested checkpoint step to publish")
            return 2
    draft = None
    if args.draft_job_id:
        draft = pub.draft_pointer(args.draft_job_id, args.draft_step)
        if draft is None:
            logger.error("[DEPLOY] no manifested draft checkpoint step "
                         "to publish")
            return 2
    ptr = pub.publish(step, draft=draft)
    events.flush()
    return 0 if ptr is not None else 2


if __name__ == "__main__":
    sys.exit(main())
