"""Verified checkpoint publishing: the trainer's half of the deploy loop.

A publish is ONE atomic pointer write: ``published.json`` next to the
Orbax checkpoint root names the step directory serving should load, plus
the sha256 of that step's ``integrity.json`` — so the serving watcher can
prove the manifest it verifies against is the manifest that was published,
not a later rewrite. The pointer write follows the same atomic
tmp + fsync + rename + dir-fsync discipline as the manifest itself
(checkpoint/manager.py): a reader never observes a torn pointer, and a
crash mid-publish leaves the previous pointer intact.

The pointer optionally carries a ``draft`` sub-pointer (same fields) so a
speculative-decoding deployment can refresh target and draft weights in
the same serving-side swap.

QUANTIZE-AT-PUBLISH (``--weights-dtype int8``): the trainer side — not
the serving side — pays for quantization. The step's params are restored
once, quantized per-tensor (symmetric int8, one fp32 scale each, the same
``(int8 * scale)`` dequant rule as the paged KV pools), and written as a
weights ARTIFACT next to the checkpoint tree with its own integrity
manifest (the identical per-file size+CRC sweep a checkpoint gets). The
pointer then carries an additive ``weights`` sub-entry naming the
artifact, so old pointers still parse and a serving watcher that predates
the field just ignores it. A corrupt artifact is rejected by
verify-before-load exactly like any corrupt publish.

``python -m fault_tolerant_llm_training_tpu.deploy.publish`` republishes
any manifested step by hand — the campaign driver uses it to stage
rollbacks and chaos-corrupted publishes.
"""

import argparse
import dataclasses
import hashlib
import json
import os
import shutil
import sys
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..checkpoint.manager import (
    MANIFEST_NAME,
    _fsync_dir,
    verify_step_dir,
    write_manifest,
)
from ..obs import events
from ..obs.registry import REGISTRY
from ..utils.logging import AUDIT_PUBLISH_FMT, init_logger, logger

POINTER_NAME = "published.json"

_M_PUBLISHED = REGISTRY.counter(
    "ftl_publish_total",
    "Checkpoint pointer publishes committed by this process")
_M_PUBLISHED_STEP = REGISTRY.gauge(
    "ftl_published_step",
    "Step of the most recently published checkpoint pointer")


@dataclasses.dataclass
class Pointer:
    """One published checkpoint: what serving should load and how to
    verify it. ``path`` is the step directory relative to the checkpoint
    root (the directory holding ``published.json``); ``draft`` is an
    optional dict with the same ``step``/``job_id``/``path``/
    ``manifest_digest`` keys for the speculative draft model; ``weights``
    is an optional dict (same keys plus ``dtype``/``nbytes``) naming a
    quantized weights artifact built at publish time — additive, so
    pointers without it keep the classic restore-from-checkpoint path."""

    step: int
    job_id: str
    path: str
    manifest_digest: str
    draft: Optional[dict] = None
    weights: Optional[dict] = None
    version: int = 1
    # Tenant -> LoRA adapter sub-pointers (inference/adapters.py
    # artifacts): adapter name -> {name, step, path, manifest_digest,
    # rank, alpha}. Additive like ``weights`` — old pointers parse, old
    # watchers ignore it; each entry is verified (digest + per-file CRC
    # sweep) before any adapter pages load.
    adapters: Optional[dict] = None


def pointer_path(root: str) -> str:
    return os.path.join(os.path.abspath(root), POINTER_NAME)


def manifest_digest(step_dir: str) -> Optional[str]:
    """sha256 hex of the step's ``integrity.json`` bytes (None if the step
    has no manifest — such a step is not publishable: the watcher could
    not verify what it loads)."""
    path = os.path.join(step_dir, MANIFEST_NAME)
    if not os.path.isfile(path):
        return None
    with open(path, "rb") as fh:
        return hashlib.sha256(fh.read()).hexdigest()


def write_pointer(root: str, ptr: Pointer) -> str:
    """Atomic pointer commit, same discipline as ``write_manifest``."""
    final = pointer_path(root)
    tmp = final + ".tmp"
    with open(tmp, "w") as fh:
        json.dump(dataclasses.asdict(ptr), fh)
        fh.flush()
        os.fsync(fh.fileno())
    os.replace(tmp, final)
    _fsync_dir(os.path.dirname(final))
    return final


def read_pointer_strict(root: str) -> Optional[Pointer]:
    """Read ``published.json``: a genuinely ABSENT pointer is None, but a
    torn, unreadable or wrong-shaped one RAISES — the distinction the
    watcher's bounded retry (ft/retry.py) needs to tell "nothing published
    yet" from "transient I/O trouble worth retrying"."""
    try:
        with open(pointer_path(root)) as fh:
            data = json.load(fh)
    except FileNotFoundError:
        return None
    return Pointer(step=int(data["step"]), job_id=str(data["job_id"]),
                   path=str(data["path"]),
                   manifest_digest=str(data["manifest_digest"]),
                   draft=data.get("draft"),
                   weights=data.get("weights"),
                   version=int(data.get("version", 1)),
                   adapters=data.get("adapters"))


def read_pointer(root: str) -> Optional[Pointer]:
    """Read ``published.json`` tolerantly: a missing, torn, or
    wrong-shaped pointer reads as None (the watcher just polls again) —
    the atomic write makes torn reads near-impossible, but a reader must
    never crash the serving process over a pointer file."""
    try:
        return read_pointer_strict(root)
    except (OSError, ValueError, KeyError, TypeError):
        return None


def _verify_target(root: str, path: str, digest: str) -> Tuple[bool, str]:
    step_dir = os.path.join(os.path.abspath(root), path)
    actual = manifest_digest(step_dir)
    if actual is None:
        return False, f"published step has no integrity manifest ({path})"
    if actual != digest:
        return False, (f"manifest digest mismatch ({path}): the published "
                       f"manifest was replaced after publish")
    ok, detail = verify_step_dir(step_dir)
    if not ok:
        return False, f"integrity check failed ({path}): {detail}"
    return True, "ok"


def verify_pointer(root: str, ptr: Pointer) -> Tuple[bool, str]:
    """Verify-before-load: the published step's manifest must be the one
    that was published (sha256) AND every manifest-listed file must pass
    its size/CRC check — for the draft and weights sub-pointers too, when
    present. Returns ``(ok, detail)``."""
    ok, detail = _verify_target(root, ptr.path, ptr.manifest_digest)
    if not ok:
        return ok, detail
    if ptr.draft is not None:
        try:
            ok, detail = _verify_target(root, str(ptr.draft["path"]),
                                        str(ptr.draft["manifest_digest"]))
        except (KeyError, TypeError):
            return False, "malformed draft sub-pointer"
        if not ok:
            return False, f"draft {detail}"
    if ptr.weights is not None:
        try:
            ok, detail = _verify_target(root, str(ptr.weights["path"]),
                                        str(ptr.weights["manifest_digest"]))
        except (KeyError, TypeError):
            return False, "malformed weights sub-pointer"
        if not ok:
            return False, f"weights {detail}"
    if ptr.adapters is not None:
        try:
            entries = sorted(ptr.adapters.items())
        except AttributeError:
            return False, "malformed adapters sub-pointer"
        for name, sub in entries:
            try:
                ok, detail = _verify_target(root, str(sub["path"]),
                                            str(sub["manifest_digest"]))
            except (KeyError, TypeError):
                return False, f"malformed adapter sub-pointer ({name})"
            if not ok:
                return False, f"adapter {name} {detail}"
    return True, "ok"


def newest_manifested_step(root: str, job_id: str) -> Optional[int]:
    """Newest finalized step of ``checkpoint_{job_id}`` that carries an
    integrity manifest (the publishable set)."""
    d = os.path.join(os.path.abspath(root), f"checkpoint_{job_id}")
    if not os.path.isdir(d):
        return None
    steps = sorted((int(n) for n in os.listdir(d) if n.isdigit()),
                   reverse=True)
    for step in steps:
        if manifest_digest(os.path.join(d, str(step))) is not None:
            return step
    return None


# --- Quantized weights artifact -------------------------------------------
#
# Layout (one directory per published step, sibling of checkpoint_{job}):
#
#   weights_int8_{job_id}/{step}/
#     t0000.npy ... tNNNN.npy   int8 payload, one file per param tensor
#     weights.json              tensor table: name (path into the params
#                               tree), file, shape, original dtype, fp32
#                               scale — everything reload needs to rebuild
#                               the tree bit-for-bit in artifact precision
#     integrity.json            the SAME per-file size+CRC manifest a
#                               checkpoint step gets (write_manifest)
#
# Per-tensor symmetric quantization: scale = amax/127, q = clip(round(
# x/scale)). Dequant mirrors the KV-pool rule: (int8 * scale) -> dtype.

WEIGHTS_META_NAME = "weights.json"
WEIGHTS_QMAX = 127.0


def _flatten_params(tree, prefix=()) -> List[Tuple[str, object]]:
    """Deterministic (path, leaf) list for a nested params dict; paths are
    '/'-joined key chains, sorted so the artifact's tensor order is stable
    across publishes of the same tree."""
    if isinstance(tree, dict) or hasattr(tree, "items"):
        out: List[Tuple[str, object]] = []
        for k in sorted(tree):
            out.extend(_flatten_params(tree[k], prefix + (str(k),)))
        return out
    return [("/".join(prefix), tree)]


def _unflatten_params(items) -> dict:
    tree: dict = {}
    for name, leaf in items:
        node = tree
        *parents, last = name.split("/")
        for k in parents:
            node = node.setdefault(k, {})
        node[last] = leaf
    return tree


def quantize_tensor(arr) -> Tuple[np.ndarray, float]:
    """Symmetric per-tensor int8: returns ``(q, scale)`` with
    ``q = clip(round(arr / scale), -127, 127)`` and
    ``scale = amax / 127`` (1.0 for an all-zero tensor, so dequant is
    exact there too)."""
    a = np.asarray(arr, dtype=np.float32)
    amax = float(np.max(np.abs(a))) if a.size else 0.0
    scale = amax / WEIGHTS_QMAX if amax > 0.0 else 1.0
    q = np.clip(np.rint(a / scale), -WEIGHTS_QMAX, WEIGHTS_QMAX)
    return q.astype(np.int8), scale


def write_weights_artifact(root: str, job_id: str, step: int, params,
                           dtype: str = "int8") -> dict:
    """Quantize ``params`` and commit the artifact directory; returns the
    pointer's ``weights`` sub-entry. The build happens in a ``.tmp``
    sibling that is renamed into place only after the integrity manifest
    is written — a crash mid-build leaves no half-artifact a reader could
    mistake for a publishable one."""
    if dtype != "int8":
        raise ValueError(f"unsupported weights artifact dtype {dtype!r}")
    root = os.path.abspath(root)
    final = os.path.join(root, f"weights_{dtype}_{job_id}", str(int(step)))
    tmp = final + ".tmp"
    for d in (final, tmp):
        if os.path.isdir(d):
            shutil.rmtree(d)
    os.makedirs(tmp)
    tensors: List[Dict[str, object]] = []
    nbytes = 0
    for i, (name, leaf) in enumerate(_flatten_params(params)):
        q, scale = quantize_tensor(leaf)
        fname = f"t{i:04d}.npy"
        np.save(os.path.join(tmp, fname), q)
        tensors.append({"name": name, "file": fname,
                        "shape": list(q.shape),
                        "dtype": str(jnp_dtype_name(leaf)),
                        "scale": scale})
        nbytes += q.nbytes
    meta = {"version": 1, "dtype": dtype, "step": int(step),
            "job_id": str(job_id), "nbytes": int(nbytes),
            "tensors": tensors}
    with open(os.path.join(tmp, WEIGHTS_META_NAME), "w") as fh:
        json.dump(meta, fh)
        fh.flush()
        os.fsync(fh.fileno())
    write_manifest(tmp, int(step))
    os.rename(tmp, final)
    _fsync_dir(os.path.dirname(final))
    return {"step": int(step), "job_id": str(job_id),
            "path": os.path.relpath(final, root),
            "manifest_digest": manifest_digest(final),
            "dtype": dtype, "nbytes": int(nbytes)}


def jnp_dtype_name(leaf) -> str:
    """Original dtype of a params leaf as a string ``jnp.dtype`` round-
    trips (``bfloat16`` included, via ml_dtypes)."""
    return str(getattr(leaf, "dtype", np.dtype(np.float32)))


def load_weights_artifact(root: str, weights: dict):
    """Rebuild the params tree from a VERIFIED artifact (the caller runs
    :func:`verify_pointer` first; this function trusts the bytes).
    Dequantizes each tensor with the shared ``(int8 * scale) -> dtype``
    rule back to its original checkpoint dtype, so the tree drops into
    ``engine.reload_params`` exactly like a checkpoint restore would."""
    import jax.numpy as jnp

    art_dir = os.path.join(os.path.abspath(root), str(weights["path"]))
    with open(os.path.join(art_dir, WEIGHTS_META_NAME)) as fh:
        meta = json.load(fh)
    if meta.get("dtype") != "int8":
        raise ValueError(
            f"unsupported weights artifact dtype {meta.get('dtype')!r}")
    items = []
    for t in meta["tensors"]:
        q = np.load(os.path.join(art_dir, t["file"]))
        if list(q.shape) != list(t["shape"]) or q.dtype != np.int8:
            raise ValueError(
                f"weights artifact tensor {t['name']} geometry mismatch")
        deq = q.astype(np.float32) * np.float32(t["scale"])
        items.append((str(t["name"]),
                      jnp.asarray(deq, dtype=jnp.dtype(str(t["dtype"])))))
    return _unflatten_params(items)


def adapter_pointer(root: str, name: str,
                    art_dir: str) -> Optional[dict]:
    """Build one tenant's adapter sub-pointer from an adapter artifact
    directory (inference/adapters.py ``write_adapter_artifact`` layout:
    factor .npy files + adapter.json + integrity.json). None if the
    directory carries no manifest — such an artifact is not publishable."""
    root = os.path.abspath(root)
    art_dir = os.path.abspath(art_dir)
    digest = manifest_digest(art_dir)
    if digest is None:
        return None
    meta: dict = {}
    meta_path = os.path.join(art_dir, "adapter.json")
    if os.path.isfile(meta_path):
        with open(meta_path) as fh:
            meta = json.load(fh)
    return {"name": str(name), "step": int(meta.get("step", 0)),
            "path": os.path.relpath(art_dir, root),
            "manifest_digest": digest,
            "rank": int(meta.get("rank", 0)),
            "alpha": float(meta.get("alpha", 0.0))}


class Publisher:
    """Atomically points serving at a verified checkpoint step.

    The trainer calls :meth:`publish` after each periodic save's
    integrity sweep (training/loop.py, host 0 only); the CLI below drives
    the same path by hand. A chaos injector hooks the moment AFTER the
    pointer commit (``publish_corrupt``) so campaigns can prove the
    serving watcher rejects a corrupted publish.
    """

    def __init__(self, checkpoint_path: str, job_id: str, chaos=None):
        self.root = os.path.abspath(checkpoint_path)
        self.job_id = str(job_id)
        self.chaos = chaos

    def step_dir(self, step: int, job_id: Optional[str] = None) -> str:
        return os.path.join(self.root, f"checkpoint_{job_id or self.job_id}",
                            str(step))

    def publish(self, step: int, draft: Optional[dict] = None,
                weights: Optional[dict] = None,
                adapters: Optional[dict] = None) -> Optional[Pointer]:
        """Publish ``step`` (which must carry an integrity manifest);
        returns the committed pointer, or None if the step is not
        publishable. ``draft`` is an optional pre-built draft sub-pointer
        dict (see :func:`draft_pointer`); ``weights`` an optional
        pre-built weights sub-entry (see :meth:`quantize_weights`);
        ``adapters`` an optional name -> sub-pointer map (see
        :func:`adapter_pointer`)."""
        step_dir = self.step_dir(step)
        digest = manifest_digest(step_dir)
        if digest is None:
            logger.warning(
                f"[DEPLOY] step {step} has no integrity manifest under "
                f"{step_dir}; not publishing")
            return None
        ptr = Pointer(step=int(step), job_id=self.job_id,
                      path=os.path.relpath(step_dir, self.root),
                      manifest_digest=digest, draft=draft, weights=weights,
                      adapters=adapters)
        write_pointer(self.root, ptr)
        _M_PUBLISHED.inc()
        _M_PUBLISHED_STEP.set(int(step))
        events.emit_audit(
            logger,
            AUDIT_PUBLISH_FMT.format(step=int(step), digest=digest[:12]),
            "publish", step=int(step), digest=digest, path=ptr.path,
            draft=bool(draft), weights=bool(weights),
            adapters=sorted(adapters) if adapters else [])
        events.flush()
        if self.chaos is not None:
            # post-commit corruption window: the pointer is live, the
            # files it names get flipped — exactly what verify-before-load
            # exists to catch
            self.chaos.on_publish(step_dir, int(step), logger)
        return ptr

    def draft_pointer(self, job_id: str,
                      step: Optional[int] = None) -> Optional[dict]:
        """Build a draft sub-pointer for a draft trained into the same
        checkpoint root (its own ``checkpoint_{job_id}``)."""
        if step is None:
            step = newest_manifested_step(self.root, job_id)
            if step is None:
                return None
        step_dir = self.step_dir(step, job_id=job_id)
        digest = manifest_digest(step_dir)
        if digest is None:
            return None
        return {"step": int(step), "job_id": str(job_id),
                "path": os.path.relpath(step_dir, self.root),
                "manifest_digest": digest}

    def quantize_weights(self, step: int, cfg,
                         dtype: str = "int8") -> Optional[dict]:
        """Restore ``step``'s params (the same cross-topology path serving
        uses) and stage a quantized weights artifact for it; returns the
        pointer's ``weights`` sub-entry, or None if the restore landed on
        a different step (the artifact must be the step the pointer
        names, never a silent fallback)."""
        from ..inference.engine import restore_params

        params, got = restore_params(self.root, self.job_id, cfg,
                                     step=int(step))
        if got != int(step):
            logger.warning(
                f"[DEPLOY] weights restore fell back to step {got}; not "
                f"staging a quantized artifact for step {step}")
            return None
        return write_weights_artifact(self.root, self.job_id, int(step),
                                      params, dtype=dtype)


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="fault_tolerant_llm_training_tpu.deploy.publish",
        description="(Re)publish a checkpoint step to published.json — "
                    "the pointer serving's hot-reload watcher follows.")
    p.add_argument("--checkpoint-path", required=True,
                   help="directory passed to training's --checkpoint-path")
    p.add_argument("--job-id", required=True,
                   help="job id the checkpoint was written under")
    p.add_argument("--step", type=int, default=None,
                   help="step to publish (default: newest manifested)")
    p.add_argument("--draft-job-id", default="",
                   help="also publish a draft sub-pointer from this job's "
                        "checkpoints (same checkpoint root)")
    p.add_argument("--draft-step", type=int, default=None,
                   help="draft step (default: newest manifested)")
    p.add_argument("--weights-dtype", default="bf16",
                   choices=("bf16", "int8"),
                   help="bf16 (default): pointer only, serving restores "
                        "the checkpoint itself. int8: also stage a "
                        "per-tensor-quantized weights artifact (own CRC "
                        "manifest) and point serving at it — the reload "
                        "swap then never touches the full-precision "
                        "checkpoint")
    p.add_argument("--model", default="tiny",
                   help="model preset of the published checkpoint (only "
                        "used by --weights-dtype int8 to rebuild the "
                        "abstract tree for the one-time restore)")
    p.add_argument("--vocab-size", type=int, default=0,
                   help="vocab size the checkpoint was trained with "
                        "(required with --weights-dtype int8)")
    p.add_argument("--layer-impl", default="loop",
                   help="layer_impl the checkpoint was trained with "
                        "(only used by --weights-dtype int8)")
    p.add_argument("--adapter", action="append", default=[],
                   metavar="NAME=DIR",
                   help="attach a tenant LoRA adapter sub-pointer: NAME "
                        "is the adapter id requests name, DIR the "
                        "CRC-manifested adapter artifact directory "
                        "(inference/adapters.py layout). Repeatable.")
    p.add_argument("--chaos", default="",
                   help="fault schedule keyed by the published step "
                        "(publish_corrupt only)")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--event-log", default="",
                   help="flight-recorder JSONL path ('' = disabled)")
    args = p.parse_args(argv)

    init_logger()
    if args.event_log:
        events.configure(args.event_log, job="publish", host=os.getpid())
    chaos = None
    if args.chaos:
        from ..chaos import ChaosInjector, parse_schedule

        chaos = ChaosInjector(
            parse_schedule(args.chaos, allowed=("publish_corrupt",)),
            seed=args.seed)
    pub = Publisher(args.checkpoint_path, args.job_id, chaos=chaos)
    step = args.step
    if step is None:
        step = newest_manifested_step(args.checkpoint_path, args.job_id)
        if step is None:
            logger.error("[DEPLOY] no manifested checkpoint step to publish")
            return 2
    draft = None
    if args.draft_job_id:
        draft = pub.draft_pointer(args.draft_job_id, args.draft_step)
        if draft is None:
            logger.error("[DEPLOY] no manifested draft checkpoint step "
                         "to publish")
            return 2
    weights = None
    if args.weights_dtype != "bf16":
        if args.vocab_size <= 0:
            logger.error("[DEPLOY] --weights-dtype int8 needs "
                         "--vocab-size to rebuild the restore tree")
            return 2
        from ..models.configs import get_config

        cfg = get_config(args.model, vocab_size=args.vocab_size,
                         layer_impl=args.layer_impl)
        weights = pub.quantize_weights(step, cfg,
                                       dtype=args.weights_dtype)
        if weights is None:
            logger.error("[DEPLOY] could not stage the quantized weights "
                         "artifact; not publishing")
            return 2
    adapters = None
    if args.adapter:
        adapters = {}
        for spec in args.adapter:
            name, _, art_dir = spec.partition("=")
            if not name or not art_dir:
                logger.error(f"[DEPLOY] malformed --adapter {spec!r} "
                             f"(want NAME=DIR)")
                return 2
            sub = adapter_pointer(args.checkpoint_path, name, art_dir)
            if sub is None:
                logger.error(f"[DEPLOY] adapter artifact {art_dir} has no "
                             f"integrity manifest; not publishing")
                return 2
            adapters[name] = sub
    ptr = pub.publish(step, draft=draft, weights=weights,
                      adapters=adapters)
    events.flush()
    return 0 if ptr is not None else 2


if __name__ == "__main__":
    sys.exit(main())
