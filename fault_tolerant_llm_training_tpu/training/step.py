"""The jitted training step (ref hot loop: train.py:92-117).

Everything the reference does per step — forward, sum-reduced fp32
cross-entropy normalized by the valid-token count, backward, global-norm clip,
AdamW + schedule — is one pure function compiled once by XLA. The reference's
``torch.compile`` flag (train.py:61-63) has no equivalent switch: compilation
is the default mode on TPU, not an option.
"""

from typing import Tuple

import jax
import jax.numpy as jnp
import optax

from ..ops.ring_attention import zigzag_layout_active, zigzag_perm
from ..parallel.mesh import mesh_axis_size
from ..training.state import TrainState
from ..utils.grad_clip import clip_grads_with_norm

IGNORE_INDEX = -100  # ref: dataset.py:50, train.py:94,101


def masked_mean_nll(nll, labels) -> Tuple[jax.Array, jax.Array]:
    """Sum per-token nll over non-ignored labels / their count (the
    reference's loss normalization, train.py:94,101-102) — the single
    assembly shared by every CE form. Returns (loss, num_valid)."""
    valid = labels != IGNORE_INDEX
    num_valid = jnp.sum(valid)
    loss = jnp.sum(jnp.where(valid, nll, 0.0)) / jnp.maximum(num_valid, 1)
    return loss, num_valid


def cross_entropy_loss(logits: jax.Array, labels: jax.Array,
                       ce_block: int | None = None
                       ) -> Tuple[jax.Array, jax.Array]:
    """Sum-reduced fp32 CE over flattened (B*S, V) logits, divided by the
    number of non-ignored label tokens (ref: train.py:94,101-102).

    ``ce_block``: None = auto (vocab-blocked CE at vocab >= 64k, dense
    below); 0 = force dense; >0 = force that vocab block size. The blocked
    path (ops/cross_entropy.py) never materializes a (B, S, V) fp32 tensor
    — at the reference's 131k vocab the fp32 logits cast is the largest
    tensor in the step. When the vocab axis is actually SHARDED (tensor /
    pipe meshes), auto stays dense: the dense form below is gather-free
    and partitions cleanly, while the blocked slicing would make the
    partitioner all-gather the logits.

    Returns (loss, num_valid_tokens).
    """
    from ..ops.cross_entropy import (
        AUTO_THRESHOLD,
        DEFAULT_BLOCK,
        chunked_softmax_xent,
    )
    from ..parallel.sharding import shard_size
    valid = labels != IGNORE_INDEX
    safe_labels = jnp.where(valid, labels, 0)
    if ce_block is None:
        v = logits.shape[-1]
        ce_block = (DEFAULT_BLOCK if v >= AUTO_THRESHOLD
                    and shard_size(v, "vocab") == 1 else 0)
    if ce_block:
        nll = chunked_softmax_xent(logits, safe_labels, ce_block)
    elif shard_size(logits.shape[-1], "vocab") > 1:
        # Vocab-sharded logits (tensor / pipe meshes): pick the label logit
        # with a masked iota reduction — every op partitions cleanly, where
        # a take_along_axis gather over the sharded vocab would force the
        # partitioner to all-gather the logits.
        lf = logits.astype(jnp.float32)
        m = jax.lax.stop_gradient(jnp.max(lf, axis=-1))
        lse = m + jnp.log(jnp.sum(jnp.exp(lf - m[..., None]), axis=-1))
        hit = (jax.lax.broadcasted_iota(jnp.int32, lf.shape, lf.ndim - 1)
               == safe_labels[..., None])
        picked = jnp.sum(jnp.where(hit, lf, 0.0), axis=-1)
        nll = lse - picked
    else:
        # logsumexp-minus-picked-logit form: identical to
        # -log_softmax[label] but the V axis is reduced away immediately
        # (no (B, S, V) fp32 log-probability tensor; SURVEY.md §2.2).
        # Measured ~1% faster than the iota form on the single-chip
        # headline bench, so the replicated-vocab case keeps it.
        nll = optax.softmax_cross_entropy_with_integer_labels(
            logits.astype(jnp.float32), safe_labels)
    return masked_mean_nll(nll, labels)


def make_optimizer(learning_rate: float, warmup_steps: int,
                   lr_schedule: str = "constant", decay_steps: int = 0
                   ) -> optax.GradientTransformation:
    """AdamW with torch defaults (ref: train.py:68 uses torch.optim.AdamW
    defaults: betas (0.9, 0.999), eps 1e-8, weight_decay 0.01) under the
    reference's linear-warmup-constant schedule (ref: utils.py:32-56), or
    warmup-cosine (``lr_schedule="cosine"``, decaying over ``decay_steps``
    — a beyond-parity option). Gradient clipping is applied *before* this
    transform with the torch coefficient semantics (utils/grad_clip.py)."""
    from ..utils.schedules import build_schedule
    schedule = build_schedule(learning_rate, warmup_steps, lr_schedule,
                              decay_steps)
    return optax.adamw(learning_rate=schedule, b1=0.9, b2=0.999, eps=1e-8,
                       weight_decay=0.01)


def model_loss(model, params, inputs, labels, microbatches: int = 0,
               train: bool = True) -> Tuple[jax.Array, jax.Array]:
    """Forward + CE, shared by the train and eval steps (so the sequence-
    layout, pipeline, and MoE handling below can never diverge between
    them). With MoE and ``train=True`` the routers' load-balancing aux
    losses (sown into the 'losses' collection, models/moe.py) are added
    with weight ``cfg.moe_aux_weight``; eval reports pure CE.

    Returns (mean loss, num_valid_tokens)."""
    sp = mesh_axis_size("sequence")
    cfg = getattr(model, "cfg", None)
    if (cfg is not None and cfg.layer_impl == "scan"
            and mesh_axis_size("pipe") > 1):
        if cfg.moe_experts and train:
            # Only the GPipe-schedule TRAIN path lands here (1F1B trains
            # via pipeline_value_and_grad, which carries the aux; eval
            # reports pure CE and needs no aux). Guard at the point of the
            # drop, not only in the Trainer.
            raise NotImplementedError(
                "--pp-schedule gpipe with an MoE model would silently "
                "drop the router load-balancing loss; use the 1f1b "
                "schedule (the default)")
        from ..parallel.pipeline import pipeline_apply
        logits = pipeline_apply(model, params, inputs,
                                microbatches=microbatches)
        return cross_entropy_loss(logits, labels)
    args = ()
    if cfg is not None and zigzag_layout_active(cfg, inputs.shape[1], sp):
        # Zigzag sequence layout (ops/ring_attention.py): permute the
        # token stream once so each sequence shard holds one early + one
        # mirrored late chunk; RoPE gets true positions, and the summed
        # CE below is permutation-invariant, so only attention's ring
        # schedule sees the layout.
        perm = jnp.asarray(zigzag_perm(inputs.shape[1], sp))
        inputs, labels = inputs[:, perm], labels[:, perm]
        args = (jnp.broadcast_to(perm[None, :], inputs.shape),)
    from ..ops.cross_entropy import AUTO_THRESHOLD
    from ..ops.fused_ce import (
        auto_min_bytes,
        fused_head_xent,
        sharded_fused_head_xent,
    )
    from ..parallel.sharding import shard_size
    # Per-DEVICE logits + cotangent footprint: batch, seq AND vocab shard
    # over their mesh axes, so the global product overestimates on
    # multi-chip meshes (OOM is a per-device phenomenon).
    vocab_shards = (shard_size(cfg.vocab_size, "vocab")
                    if cfg is not None else 1)
    logits_bytes = (
        inputs.shape[0] // shard_size(inputs.shape[0], "batch")
        * (inputs.shape[1] // shard_size(inputs.shape[1], "seq"))
        * (cfg.vocab_size // vocab_shards if cfg is not None else 0) * 6)
    fused = (cfg is not None and cfg.vocab_size >= AUTO_THRESHOLD
             and logits_bytes > auto_min_bytes())

    # One forward (with the MoE routers' sown aux when training), one loss
    # assembly — the fused path only changes WHICH function maps the
    # forward's output to per-token nll, so masking/normalization and the
    # aux handling cannot diverge between the paths.
    method = "hidden_states" if fused else None
    if cfg is not None and cfg.moe_experts and train:
        out, mutated = model.apply({"params": params}, inputs, *args,
                                   method=method, mutable=["losses"])
        aux = sum(jnp.sum(leaf) for leaf in
                  jax.tree_util.tree_leaves(mutated))
    else:
        out = model.apply({"params": params}, inputs, *args, method=method)
        aux = None
    if fused:
        # Large vocab whose per-device logits + cotangent would not fit:
        # block the head matmul into the loss (ops/fused_ce.py) — logits
        # never materialize in any dtype. A sharded vocab axis (tensor /
        # pipe meshes) takes the shard_map form whose online stats fold
        # across the shards. See AUTO_MIN_BYTES for the measured tradeoff.
        head_w = params["output"]["kernel"].astype(cfg.dtype)
        safe = jnp.where(labels == IGNORE_INDEX, 0, labels)
        xent = (sharded_fused_head_xent if vocab_shards > 1
                else fused_head_xent)
        nll = xent(out, head_w, safe,
                   min(8192, head_w.shape[1] // vocab_shards))
        loss, num_valid = masked_mean_nll(nll, labels)
    else:
        loss, num_valid = cross_entropy_loss(out, labels)
    if aux is not None:
        loss = loss + cfg.moe_aux_weight * aux
    return loss, num_valid


def make_eval_step(model, microbatches: int = 0, grad_accum: int = 1):
    """Forward-only loss for held-out evaluation (no reference counterpart —
    the reference never evaluates; SURVEY.md §5.5 notes loss is its only
    metric). Returns packed (sum_nll, num_valid) as one fp32 array so the
    host aggregates exactly across batches with one D2H transfer each:
    mean = sum(sum_nll) / sum(num_valid), weighting every token equally
    even when batches carry different pad counts.

    ``grad_accum > 1`` slices the eval batch through the same ``lax.scan``
    accumulation as the train step: a run that needs accumulation to fit
    activation memory must not get an eval pass with a grad_accum-fold
    larger activation footprint at the first --eval-frequency boundary."""

    def eval_one(params, inputs, labels):
        loss, num_valid = model_loss(model, params, inputs, labels,
                                     microbatches, train=False)
        return loss * num_valid, num_valid

    def eval_step(params, inputs, labels):
        if grad_accum <= 1:
            nll, n = eval_one(params, inputs, labels)
            return jnp.stack((nll, n.astype(jnp.float32)))
        b = inputs.shape[0] // grad_accum
        sl_inputs = inputs.reshape(grad_accum, b, *inputs.shape[1:])
        sl_labels = labels.reshape(grad_accum, b, *labels.shape[1:])

        def body(carry, sl):
            nll_acc, n_acc = carry
            nll, n = eval_one(params, sl[0], sl[1])
            return (nll_acc + nll, n_acc + n), None

        (nll, n), _ = jax.lax.scan(
            body, (jnp.zeros((), jnp.float32), jnp.zeros((), jnp.int32)),
            (sl_inputs, sl_labels))
        return jnp.stack((nll, n.astype(jnp.float32)))

    return eval_step


def make_train_step(model, optimizer: optax.GradientTransformation,
                    grad_max_norm: float, microbatches: int = 0,
                    grad_accum: int = 1):
    """Build the pure ``(state, inputs, labels) -> (state, metrics)`` step.

    metrics: loss (fp32), grad_norm (fp32; host checks finiteness — the
    torch ``error_if_nonfinite`` raise cannot live inside jit, ref:
    utils.py:61), num_tokens, and packed = stack((loss, grad_norm)) — the
    single leaf the host loop fetches per step (one D2H transfer).
    ``microbatches`` only matters under pipeline parallelism (0 = one
    microbatch per stage).

    ``grad_accum > 1`` splits the batch into that many slices and runs
    them through one ``lax.scan`` (peak activation memory drops by the
    factor), accumulating token-weighted gradients in fp32 — exactly the
    big-batch semantics of the reference's sum-CE / valid-token loss
    (train.py:101-102): slices with more valid tokens weigh more.
    """

    def loss_fn(params, inputs, labels):
        return model_loss(model, params, inputs, labels, microbatches)

    cfg = getattr(model, "cfg", None)
    if (cfg is not None and cfg.layer_impl == "scan"
            and mesh_axis_size("pipe") > 1 and cfg.pp_schedule == "1f1b"):
        # 1F1B assembles gradients explicitly inside its tick loop
        # (parallel/pipeline.py) — autodiff never sees the schedule.
        from ..parallel.pipeline import pipeline_value_and_grad

        def value_and_grad(params, inputs, labels):
            return pipeline_value_and_grad(model, params, inputs, labels,
                                           microbatches=microbatches)
    else:
        value_and_grad = jax.value_and_grad(loss_fn, has_aux=True)

    def accum_value_and_grad(params, inputs, labels):
        if grad_accum <= 1:
            return value_and_grad(params, inputs, labels)
        b = inputs.shape[0] // grad_accum
        sl_inputs = inputs.reshape(grad_accum, b, *inputs.shape[1:])
        sl_labels = labels.reshape(grad_accum, b, *labels.shape[1:])

        def body(carry, sl):
            g_acc, nll_acc, n_acc = carry
            (loss, n), grads = value_and_grad(params, sl[0], sl[1])
            nf = n.astype(jnp.float32)
            g_acc = jax.tree_util.tree_map(
                lambda a, g: a + g.astype(jnp.float32) * nf, g_acc, grads)
            return (g_acc, nll_acc + loss * nf, n_acc + n), None

        init = (jax.tree_util.tree_map(
            lambda p: jnp.zeros(p.shape, jnp.float32), params),
            jnp.zeros((), jnp.float32), jnp.zeros((), jnp.int32))
        (g_acc, nll, n_tot), _ = jax.lax.scan(body, init,
                                              (sl_inputs, sl_labels))
        denom = jnp.maximum(n_tot.astype(jnp.float32), 1.0)
        grads = jax.tree_util.tree_map(
            lambda g, p: (g / denom).astype(p.dtype), g_acc, params)
        return (nll / denom, n_tot), grads

    def train_step(state: TrainState, inputs: jax.Array, labels: jax.Array):
        (loss, num_tokens), grads = accum_value_and_grad(
            state.params, inputs, labels)
        grads, grad_norm = clip_grads_with_norm(grads, grad_max_norm)
        updates, new_opt_state = optimizer.update(grads, state.opt_state,
                                                  state.params)
        new_params = optax.apply_updates(state.params, updates)
        new_state = state.replace(step=state.step + 1, params=new_params,
                                  opt_state=new_opt_state)
        metrics = {"loss": loss, "grad_norm": grad_norm,
                   "num_tokens": num_tokens,
                   # (loss, grad_norm) as one array: the host loop fetches
                   # this single leaf per step — one D2H RPC on tunneled
                   # transports instead of one per scalar (training/loop.py).
                   "packed": jnp.stack((loss, grad_norm))}
        return new_state, metrics

    return train_step
