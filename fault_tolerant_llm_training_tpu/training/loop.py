"""Training orchestration (ref: train.py:12-129).

Setup order mirrors the reference (checkpoint -> data -> model -> optimizer ->
resume bookkeeping, ref train.py:20-84) with the TPU-native differences:

- signal handlers are installed *before* setup and checked at phase
  boundaries, closing the reference's fatal unprotected-setup window
  (SURVEY.md §3.2);
- resume restores the data-iterator position from the checkpoint in O(1)
  instead of replaying N batches (ref: train.py:36-39);
- the hot loop dispatches the jitted step asynchronously with a bounded
  in-flight window (``--inflight``): dispatch stays pipelined (the reference
  blocks on ``loss.item()`` every log step) while "current step" remains
  well-defined within the 120 s preemption budget (SURVEY.md §7.3 #1);
- a non-finite gradient norm raises on the host when the metric is consumed —
  same fault path as the reference's ``error_if_nonfinite`` (utils.py:61),
  shifted out of the jitted region.
"""

import collections
import contextlib
import math
import os
import threading
import time
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding

from ..chaos.injector import ChaosInjector
from ..checkpoint.manager import CheckpointManager, update_checkpoint_age_gauge
from ..data.collator import CollatorForCLM
from ..data.loader import DataLoader
from ..data.parquet import IterableParquetDataset, ParquetDataset
from ..data.prefetch import DevicePrefetcher
from ..data.tokenizer import load_tokenizer
from ..ft import multihost
from ..ft.multihost import PeerHostError, barrier
from ..ft.signals import SignalFlag, TrainingSignal
from ..models import Transformer, get_config
from ..deploy.publish import Publisher
from ..obs import events
from ..obs.registry import REGISTRY
from ..obs.trace import AutoTraceWindow, TraceWindow
from ..parallel.mesh import make_mesh, use_mesh
from ..parallel.sharding import batch_pspec, param_pspecs
from ..training.state import TrainState
from ..training.step import make_eval_step, make_optimizer, make_train_step
from ..utils.compile_cache import enable_compilation_cache
from ..utils.config import JOBID, TrainConfig
from ..utils.dtypes import PRECISION_STR_TO_DTYPE
from ..utils.grad_clip import NonFiniteGradientError
from ..utils.logging import (
    AUDIT_RESUME_FMT,
    AUDIT_START,
    AUDIT_STEP_FMT,
    AUDIT_TRACE_AUTO_FMT,
    logger,
)
from ..utils.metrics import (
    Throughput,
    device_peak_flops,
    hbm_usage_str,
    mfu,
    per_device_memory_stats,
    transformer_flops_per_token,
)

# Shared never-set token for watchdog callbacks run directly (single-process
# and re-entrant paths) — they receive a cancellation event they can ignore.
_NEVER_CANCELLED = threading.Event()


class Trainer:
    def __init__(self, cfg: TrainConfig, signal_flag: Optional[SignalFlag] = None):
        self.cfg = cfg
        self.state = None
        self.training_step = 0
        self._resumed = False
        self._last_data_state = None
        # first periodic save blocks to observe real write wall (see _loop)
        self._budget_observed = False
        # True when the raised error is deterministic and hits every host at
        # the same step (injection, non-finite grad from replicated metrics)
        # — only then may the exit handler run a *coordinated* save on a pod.
        self.error_is_replicated = False
        self._mesh_ctx = None
        # Dispatched-but-unfinished steps (filled by _loop; exists from
        # construction so save_checkpoint can drain it on setup-phase saves).
        self._inflight = collections.deque()
        self._batch_iter = None  # live prefetch iterator (fence catch-up)
        self._in_guard = False  # re-entrancy latch for _guarded_wait
        # One long-lived bounded-wait worker: _guarded_wait runs every
        # training step (metric consume), so per-call thread spawn/join
        # (watchdog) would churn a thread per step (ADVICE r5).
        self._waiter = multihost.PersistentWaiter()
        self._fence_done = False  # fence ran; stale err keys must not re-raise
        self._signal_round = 0  # KV signal-agreement round (sync boundaries)
        self._est_save_seconds = None  # startup write-probe estimate

        # Handlers first — signals during the (potentially long) setup are
        # deferred and handled at the next phase boundary instead of killing
        # the process (the reference registers only at train.py:89-90).
        self.signal_flag = signal_flag or SignalFlag()
        if signal_flag is None:
            self.signal_flag.register()

        logger.info(f"Experiment args: {cfg}")  # ref: train.py:14

        if cfg.distributed:
            # jax.distributed auto-detects Slurm/TPU-pod topologies; outside
            # those (e.g. a hand-launched multi-process CPU run) the JAX_*
            # env vars spell it out explicitly.
            kwargs = {}
            explicit = ("JAX_COORDINATOR_ADDRESS", "JAX_NUM_PROCESSES",
                        "JAX_PROCESS_ID")
            present = [v for v in explicit if v in os.environ]
            if present and len(present) != len(explicit):
                raise ValueError(
                    f"explicit jax.distributed config needs all of "
                    f"{explicit}; missing "
                    f"{sorted(set(explicit) - set(present))}")
            if present:
                # Explicit config must also disable cluster sniffing:
                # jax's Slurm detector triggers on SLURM_JOB_ID alone (set
                # for checkpoint naming even off-Slurm) and then dies on
                # the missing SLURM_LOCALID.
                kwargs = dict(
                    coordinator_address=os.environ["JAX_COORDINATOR_ADDRESS"],
                    num_processes=int(os.environ["JAX_NUM_PROCESSES"]),
                    process_id=int(os.environ["JAX_PROCESS_ID"]),
                    cluster_detection_method="deactivate")
            jax.distributed.initialize(**kwargs)
        # Multihost: in-loop signal checks are cluster-wide agreements
        # (ft/multihost.py) so all hosts raise at the same boundary; setup
        # checks are local-only and skipped on pods (see _setup_check).
        self._sync_signals = jax.process_count() > 1

        # Flight recorder (obs/events.py): configured before any phase that
        # can fault, so a signal during setup still leaves a JSONL trail
        # the goodput stitcher can read. Same job-id naming contract as the
        # checkpoints (checkpoint_{JOBID} <-> events_{JOBID}.jsonl).
        self._job_id = JOBID or "local"
        events.configure(cfg.event_log_path(self._job_id),
                         job=self._job_id, host=jax.process_index())
        self._init_metrics()

        # Chaos injectors (chaos/): the parsed --chaos schedule plus the
        # legacy --raise-error alias, seeded by --seed. None = no chaos.
        self.chaos = ChaosInjector.from_config(cfg)
        if self.chaos is not None:
            logger.info(f"Chaos schedule | {self.chaos.describe()}")

        self.mesh = make_mesh(cfg.dp, cfg.fsdp, cfg.sp, cfg.tp, pp=cfg.pp,
                              ep=cfg.ep)
        if cfg.pp > 1:
            if cfg.layer_impl != "scan":
                raise ValueError(
                    "--pp needs --layer-impl scan (pipeline stages shard "
                    "the layer-stacked params; parallel/pipeline.py)")
            if cfg.sp > 1:
                raise ValueError("--pp with --sp is not supported")
            micro = cfg.microbatches or cfg.pp
            if cfg.batch_size % micro:
                raise ValueError(
                    f"--batch-size {cfg.batch_size} not divisible by "
                    f"microbatches {micro}")
        if cfg.grad_accum > 1:
            if cfg.batch_size % cfg.grad_accum:
                raise ValueError(
                    f"--batch-size {cfg.batch_size} not divisible by "
                    f"--grad-accum {cfg.grad_accum}")
            slice_batch = cfg.batch_size // cfg.grad_accum
            data_ways_ = self.mesh.shape["data"] * self.mesh.shape["fsdp"]
            if slice_batch % data_ways_:
                raise ValueError(
                    f"per-slice batch {slice_batch} (= --batch-size / "
                    f"--grad-accum) is not divisible by the data-sharding "
                    f"extent dp*fsdp = {data_ways_}")
            if cfg.pp > 1 and slice_batch % (cfg.microbatches or cfg.pp):
                raise ValueError(
                    f"per-slice batch {slice_batch} is not divisible by "
                    f"the pipeline microbatch count "
                    f"{cfg.microbatches or cfg.pp}")
        data_ways = (self.mesh.shape["data"] * self.mesh.shape["fsdp"])
        if cfg.batch_size % data_ways:
            raise ValueError(
                f"--batch-size {cfg.batch_size} is not divisible by the "
                f"data-sharding extent dp*fsdp = {data_ways} "
                f"(mesh {dict(self.mesh.shape)}); pick a batch size that "
                f"divides evenly or reduce --dp/--fsdp")
        if cfg.sequence_length % self.mesh.shape["sequence"]:
            raise ValueError(
                f"--sequence-length {cfg.sequence_length} is not divisible "
                f"by the sequence-parallel extent sp = "
                f"{self.mesh.shape['sequence']}")
        self._mesh_ctx = use_mesh(self.mesh)
        self._mesh_ctx.__enter__()

        # Resume source (ref: train.py:20-24): the chained job passes the
        # *previous* job's id; its checkpoints live in checkpoint_{id}/.
        read_mngr = None
        if cfg.checkpoint_id:
            logger.info(f"Loading checkpoint from {cfg.checkpoint_path}")
            read_mngr = CheckpointManager(cfg.checkpoint_path, cfg.checkpoint_id)
        self._setup_check()

        # --- data (ref: train.py:27-34) ---
        logger.info("Setting up DataLoaders...")
        self.tokenizer = load_tokenizer(cfg.tokenizer_name_or_path)
        shuffle_seed = cfg.seed if cfg.shuffle else None
        # Automatic eval holdout (VERDICT r4 weak #6): with --eval-frequency
        # but no --eval-dataset, the first batch*eval_batches corpus rows
        # become the eval set and are carved OUT of the training index
        # (both map and packed paths), so "held-out" means held out.
        self._holdout_rows = 0
        if cfg.eval_frequency and not cfg.eval_dataset:
            self._holdout_rows = cfg.batch_size * cfg.eval_batches
            logger.info(f"Eval holdout: first {self._holdout_rows} corpus "
                        f"rows reserved for evaluation and excluded from "
                        f"training")
        if cfg.data_loading == "map":
            dataset = ParquetDataset(cfg.dataset, self.tokenizer,
                                     cfg.sequence_length,
                                     cfg.batch_size * cfg.training_steps,
                                     pretokenize_dir=cfg.pretokenize_dir,
                                     shuffle_seed=shuffle_seed,
                                     holdout_rows=self._holdout_rows,
                                     shuffle_impl=cfg.shuffle_impl)
            collator = CollatorForCLM(cfg.sequence_length,
                                      self.tokenizer.pad_token_id)
            # Pod default: each host tokenizes only its own devices' rows
            # (VERDICT r4 weak #2; bit-identical trajectory to replicated,
            # tests/test_sharded_data.py). Single process: replicated is
            # the same work, skip the indirection unless forced.
            sharded = (cfg.data_sharding == "host"
                       or (cfg.data_sharding == "auto"
                           and jax.process_count() > 1))
            if sharded:
                from ..data.loader import HostShardedDataLoader

                self.loader = HostShardedDataLoader(
                    dataset, cfg.batch_size, collator,
                    NamedSharding(self.mesh, batch_pspec()),
                    cfg.sequence_length)
            else:
                self.loader = DataLoader(dataset, cfg.batch_size, collator)
        else:
            if cfg.data_sharding == "host":
                raise ValueError(
                    "--data-sharding host needs --data-loading map (the "
                    "packed path's token buffer is a sequential walk; "
                    "per-host row sharding is ill-defined there)")
            dataset = IterableParquetDataset(
                cfg.dataset, self.tokenizer, cfg.sequence_length,
                bos_token_id=self.tokenizer.bos_token_id,
                legacy=cfg.legacy_packing, shuffle_seed=shuffle_seed,
                holdout_rows=self._holdout_rows,
                shuffle_impl=cfg.shuffle_impl)
            self.loader = DataLoader(dataset, cfg.batch_size)
        self._setup_check()

        # --- model + optimizer (ref: train.py:42-77) ---
        logger.info("Setting up Model...")
        dtype = PRECISION_STR_TO_DTYPE[cfg.model_dtype]
        param_dtype = (jnp.float32 if cfg.master_weights == "fp32" else dtype)
        vocab = cfg.vocab_size or self.tokenizer.vocab_size
        moe_over = {k: v for k, v in dict(
            moe_experts=cfg.moe_experts, moe_top_k=cfg.moe_top_k,
            moe_capacity_factor=cfg.moe_capacity_factor,
            moe_aux_weight=cfg.moe_aux_weight,
            moe_impl=cfg.moe_impl).items() if v is not None}
        self.model_config = get_config(
            cfg.model, vocab_size=vocab, seq_len=cfg.sequence_length,
            dtype=dtype, param_dtype=param_dtype,
            attention_impl=cfg.attention_impl, embed_impl=cfg.embed_impl,
            sp_layout=cfg.sp_layout, layer_impl=cfg.layer_impl,
            pp_schedule=cfg.pp_schedule,
            pp_stage_unroll=cfg.pp_stage_unroll,
            remat=cfg.remat, **moe_over)
        if cfg.ep > 1 and not self.model_config.moe_experts:
            raise ValueError("--ep needs an MoE model (--model tiny-moe or "
                             "--moe-experts N)")
        if self.model_config.moe_experts:
            if cfg.pp > 1 and cfg.pp_schedule == "gpipe":
                raise ValueError("--pp-schedule gpipe with an MoE model is "
                                 "not supported (its forward drops the "
                                 "router aux loss); use 1f1b (the default)")
            if self.model_config.moe_experts % max(cfg.ep, 1):
                raise ValueError(
                    f"moe_experts {self.model_config.moe_experts} not "
                    f"divisible by --ep {cfg.ep}")
        self.model = Transformer(self.model_config)
        self.optimizer = make_optimizer(
            cfg.learning_rate, cfg.lr_warmup_steps,
            lr_schedule=cfg.lr_schedule,
            decay_steps=cfg.lr_decay_steps or cfg.training_steps)

        dummy = jnp.zeros((1, cfg.sequence_length), jnp.int32)

        def init_fn(key):
            params = self.model.init(key, dummy)["params"]
            opt_state = self.optimizer.init(params)
            return TrainState(step=jnp.zeros((), jnp.int32), params=params,
                              opt_state=opt_state)

        abstract = jax.eval_shape(init_fn, jax.random.PRNGKey(cfg.seed))
        specs = param_pspecs(abstract)
        self.state_shardings = jax.tree_util.tree_map(
            lambda s: NamedSharding(self.mesh, s), specs,
            is_leaf=lambda x: isinstance(x, jax.sharding.PartitionSpec))
        self.abstract_state = jax.tree_util.tree_map(
            lambda a, sh: jax.ShapeDtypeStruct(a.shape, a.dtype, sharding=sh),
            abstract, self.state_shardings)
        abstract_sharded = self.abstract_state
        self._warn_if_state_exceeds_hbm(abstract_sharded)
        # MFU denominator (bench.py convention): matmul params exclude the
        # input-embedding gather; attention FLOPs causal-masked.
        n_params = sum(int(np.prod(l.shape))
                       for l in jax.tree_util.tree_leaves(abstract.params))
        self._flops_per_token = transformer_flops_per_token(
            n_params - self.model_config.vocab_size * self.model_config.dim,
            cfg.sequence_length, self.model_config.dim,
            self.model_config.n_layers, causal=True)

        if read_mngr is not None:
            t_restore = time.perf_counter()
            self.state, data_state, _ = read_mngr.restore(abstract_sharded)
            read_mngr.close()
            self.loader.set_state(data_state)
            self.training_step = int(self.state.step)
            self._last_data_state = data_state
            self._resumed = True
            restore_secs = time.perf_counter() - t_restore
            events.emit("ckpt_restore", step=self.training_step,
                        dur=restore_secs, source_job=cfg.checkpoint_id)
            self._m_restore.set(restore_secs)
            logger.info("Model loaded from checkpoint")  # ref: train.py:58
            logger.info("Optimizer loaded from checkpoint")  # ref: train.py:72
            logger.info("LR Scheduler loaded from checkpoint")  # ref: train.py:77
        else:
            self.state = jax.jit(init_fn,
                                 out_shardings=self.state_shardings)(
                jax.random.PRNGKey(cfg.seed))
            self._last_data_state = self.loader.get_state()
        # Count of step programs this host has dispatched (== state.step on
        # device). The pod fault fence converges on the cluster maximum of
        # this value — training_step lags it inside one loop iteration.
        self._dispatched = self.training_step
        self._setup_check()

        # Save manager for *this* job's id (ref naming: checkpoint_{JOBID},
        # utils.py:80) — files accumulate one dir per preemption, like the
        # reference accumulates one .ckpt per preemption.
        self._save_job_id = self._job_id
        self.ckpt_mngr = CheckpointManager(cfg.checkpoint_path,
                                           self._save_job_id,
                                           max_to_keep=cfg.checkpoint_keep)
        self._log_checkpoint_budget()
        # Deployment pointer (--publish, deploy/publish.py): host 0 commits
        # published.json after each periodic save's integrity sweep. The
        # serving watcher (deploy/reload.py) verifies the manifest before
        # it ever loads, so a torn or corrupted publish cannot take down
        # serving — publishing is fire-and-forget from the trainer's side.
        self._publisher = None
        if cfg.publish and jax.process_index() == 0:
            self._publisher = Publisher(cfg.checkpoint_path,
                                        self._save_job_id, chaos=self.chaos)

        self.batch_sharding = NamedSharding(self.mesh, batch_pspec())
        self._jit_step = jax.jit(
            make_train_step(self.model, self.optimizer, cfg.grad_max_norm,
                            microbatches=cfg.microbatches,
                            grad_accum=cfg.grad_accum),
            donate_argnums=(0,),
            out_shardings=(self.state_shardings, None))
        # AOT-compile now, inside the signal-deferred setup window: a
        # preemption signal interrupting XLA compilation can wedge native
        # code, and compilation is the longest uninterruptible stretch
        # (~35 s model build in the reference, SURVEY.md §3.2). With
        # --compile-cache-dir a warm restart replaces the compile with a
        # disk read; the timed "compile" flight-recorder event is how
        # goodput reports distinguish cold from warm builds.
        cache_on = False
        if cfg.compile_cache_dir:
            cache_on = enable_compilation_cache(cfg.compile_cache_dir)
            if cache_on:
                logger.info(f"Compilation cache | {cfg.compile_cache_dir}")
        batch_struct = jax.ShapeDtypeStruct(
            (cfg.batch_size, cfg.sequence_length), jnp.int32,
            sharding=self.batch_sharding)
        t_compile = time.perf_counter()
        self._compiled_step = self._jit_step.lower(
            self.abstract_state, batch_struct, batch_struct).compile()
        compile_secs = time.perf_counter() - t_compile
        # emitted from run(), AFTER the start/resume audit: the flight-
        # recorder trail contract is that a job's first event is
        # start/resume (tests/test_obs.py, goodput stitcher)
        self._compile_event = dict(step=self.training_step,
                                   dur=compile_secs,
                                   cache=("on" if cache_on else "off"))
        logger.info(f"Train step compiled in {compile_secs:.2f}s "
                    f"(cache {'on' if cache_on else 'off'})")
        self.prefetcher = DevicePrefetcher(
            self.loader, sharding=self.batch_sharding, depth=cfg.prefetch,
            chaos_on_batch=(self.chaos.on_batch if self.chaos else None),
            start_batch=self.training_step)
        self.throughput = Throughput(
            tokens_per_step=cfg.batch_size * cfg.sequence_length)
        if self._resumed:
            # Reset on ckpt_restore: the warmup-exclusion window restarts
            # here so the first post-resume tokens/s excludes the restore/
            # recompile wall instead of mixing it into steady state, and
            # the window is tagged so dashboards don't read the transient
            # as a regression (utils/metrics.py Throughput docstring).
            self.throughput.reset(tag="post_resume")

        # Windowed profiler capture (--trace-steps A:B, obs/trace.py). The
        # window drains the dispatch pipeline before stop_trace so the
        # final steps' async device work lands inside the capture.
        self._trace = None
        if cfg.trace_steps:
            trace_dir = cfg.profile_dir or os.path.join(
                cfg.checkpoint_path or "/tmp",
                f"traces_{self._job_id}")
            self._trace = TraceWindow(
                cfg.trace_steps, trace_dir,
                drain=lambda: self._drain_inflight(check=False))
            logger.info(f"Trace window | steps "
                        f"{self._trace.start_step}:{self._trace.stop_step} "
                        f"-> {trace_dir}")
        # Reactive capture (--auto-trace, obs/trace.py AutoTraceWindow):
        # arms once per run when a step's wall regresses past 2x the
        # rolling median. Mutually exclusive with the explicit window —
        # one profiler owner at a time (utils/config.py).
        self._auto_trace = None
        if cfg.auto_trace and not cfg.trace_steps:
            trace_dir = cfg.profile_dir or os.path.join(
                cfg.checkpoint_path or "/tmp",
                f"traces_{self._job_id}")
            self._auto_trace = AutoTraceWindow(trace_dir)
            logger.info(f"Auto-trace | armed (2x median) -> {trace_dir}")

        # /metrics endpoint (obs/prometheus.py), gated on --metrics-port.
        self._metrics_server = None
        self._heartbeat = None
        if cfg.metrics_port:
            from ..obs.prometheus import MetricsServer

            self._metrics_server = MetricsServer(port=cfg.metrics_port)
            port = self._metrics_server.start()
            logger.info(f"Metrics | serving /metrics on port {port}")
        # Per-host heartbeats run regardless of the scrape endpoint: the
        # age gauges feed the flight recorder and the straggler analysis,
        # and a host without a scraper still publishes its beat for every
        # OTHER host's gauges (utils/config.py heartbeat_seconds).
        if cfg.heartbeat_seconds > 0:
            from ..obs.prometheus import HeartbeatThread

            self._heartbeat = HeartbeatThread(
                lambda: self.training_step,
                interval_seconds=cfg.heartbeat_seconds)
            self._heartbeat.start()

        # --- held-out evaluation (no reference counterpart; SURVEY §5.5
        # notes training loss is the reference's only metric) ---
        self._compiled_eval = None
        if cfg.eval_frequency:
            if cfg.eval_batches < 1:
                raise ValueError(
                    f"--eval-batches {cfg.eval_batches} must be >= 1 when "
                    f"--eval-frequency is set")
            # Without --eval-dataset the eval set is the training corpus's
            # held-out prefix (rows [0, holdout) — see the carve above);
            # with one, it is a separate corpus read from row 0.
            eval_ds = ParquetDataset(
                cfg.eval_dataset or cfg.dataset, self.tokenizer,
                cfg.sequence_length, cfg.batch_size * cfg.eval_batches,
                pretokenize_dir=cfg.pretokenize_dir)
            self.eval_loader = DataLoader(
                eval_ds, cfg.batch_size,
                CollatorForCLM(cfg.sequence_length,
                               self.tokenizer.pad_token_id))
            self._eval_batches_cache = None  # tokenized once, first pass
            self._compiled_eval = jax.jit(
                make_eval_step(self.model,
                               microbatches=cfg.microbatches,
                               grad_accum=cfg.grad_accum)).lower(
                self.abstract_state.params, batch_struct,
                batch_struct).compile()

    def _init_metrics(self) -> None:
        """Registry handles (obs/registry.py) — created once; the hot loop
        only mutates leaf metrics. These replace the ad-hoc log-line-only
        reporting: the same numbers now export at /metrics."""
        r = REGISTRY
        self._m_step_time = r.histogram(
            "ftl_train_step_seconds",
            "Per-step wall time, consume-to-consume (pipelined dispatch "
            "makes this the steady-state step cadence)")
        self._m_tps = r.gauge(
            "ftl_train_tokens_per_sec",
            "Steady-state tokens/s; window label tags post-resume "
            "transients")
        self._m_tokens = r.counter("ftl_train_tokens_total",
                                   "Tokens trained by this process")
        self._m_loss = r.gauge("ftl_train_loss", "Training loss")
        self._m_gnorm = r.gauge("ftl_train_grad_norm",
                                "Global gradient norm")
        self._m_stepg = r.gauge("ftl_train_step",
                                "Last consumed training step")
        self._m_mfu = r.gauge(
            "ftl_train_mfu",
            "Model FLOPs utilization (0-1; TPU backends only — needs a "
            "known peak)")
        self._m_stall = r.counter(
            "ftl_data_stall_seconds_total",
            "Wall time the loop spent blocked on the input pipeline")
        self._m_save = r.histogram(
            "ftl_ckpt_save_seconds",
            "Blocking checkpoint-save wall (fault-path and first periodic)")
        self._m_saves = r.counter("ftl_ckpt_saves_total",
                                  "Checkpoints written")
        self._m_restore = r.gauge("ftl_ckpt_restore_seconds",
                                  "Checkpoint restore wall at setup")
        self._m_eval_loss = r.gauge("ftl_eval_loss",
                                    "Held-out eval loss (token-weighted)")
        self._m_hbm_used = r.gauge(
            "ftl_device_hbm_bytes_in_use",
            "Per-device HBM in use (utils/metrics.py "
            "per_device_memory_stats)")
        self._m_hbm_limit = r.gauge("ftl_device_hbm_bytes_limit",
                                    "Per-device HBM limit")
        self._last_consume_t = None
        # (wall clock, last step) already covered by a step event; the next
        # event's dur/steps are deltas against this.
        self._step_window_start = None

    def _warn_if_state_exceeds_hbm(self, abstract_sharded) -> None:
        """Pre-flight capacity estimate: warn (don't fail — remat and fusion
        change actuals) when the sharded TrainState alone exceeds a device's
        memory, instead of letting XLA die later in a raw OOM dump. No-op on
        backends that expose no memory_stats."""
        from ..utils.metrics import device_memory_stats

        _, limit = device_memory_stats()
        if not limit:
            return
        per_device = 0
        for leaf in jax.tree_util.tree_leaves(abstract_sharded):
            shard = leaf.sharding.shard_shape(leaf.shape)
            per_device += int(np.prod(shard)) * leaf.dtype.itemsize
        if per_device > limit:
            logger.warning(
                f"TrainState needs ~{per_device / 1e9:.1f} GB per device but "
                f"the device reports {limit / 1e9:.1f} GB; expect an OOM — "
                f"shard more (--fsdp/--tp) or pick a smaller --model")

    def _log_checkpoint_budget(self) -> None:
        """The startup deadline check (SURVEY §5.3, §7.3 #2): estimate the
        fault-path save time from this host's state bytes and a one-shot
        write-throughput probe of the checkpoint filesystem, and compare
        it against the scheduler's USR1 lead. The whole framework exists
        to honor that lead — discovering a blown budget at the first
        preemption is too late. Numbers are logged every run so operators
        can track drift (e.g. a slower Lustre mount)."""
        from ..checkpoint.manager import (
            estimate_save_seconds,
            measure_write_throughput,
            state_bytes,
        )

        total = state_bytes(self.abstract_state)
        # Per-host share: every host writes only its own device shards
        # (Orbax per-host parallel writes); even sharding assumed.
        per_host = total // max(jax.process_count(), 1)
        try:
            tput = measure_write_throughput(self.ckpt_mngr.directory)
        except OSError as e:
            logger.warning(f"Checkpoint budget | write probe failed: {e}")
            return
        est = estimate_save_seconds(per_host, tput)
        self._est_save_seconds = est  # sizes the healthy-save watchdog
        lead = self.cfg.signal_lead_seconds
        logger.info(
            f"Checkpoint budget | state {total / 1e9:.2f} GB "
            f"({per_host / 1e9:.2f} GB/host) | disk {tput / 1e9:.2f} GB/s "
            f"| est save {est:.0f} s | signal lead {lead} s")
        if est > lead:
            logger.warning(
                f"Checkpoint budget EXCEEDED: estimated fault-path save "
                f"{est:.0f} s > the {lead} s signal lead — a preemption "
                f"may outrun the save. Shard over more hosts, use faster "
                f"checkpoint storage, or raise --signal-lead-seconds to "
                f"match the scheduler's --signal=USR1@N.")

    def _setup_check(self) -> None:
        """Phase-boundary signal check during setup.

        Single-host: raise now, closing the reference's unprotected-setup
        window (train.py:42-84 runs ~35 s before handlers exist).
        Multihost: never raise *alone* during setup — a lone raise strands
        the other hosts in their next collective, and a collective check
        here hangs survivors if one host's setup fails. The pending signal
        (only possible from the microsecond window before ``deferred()``
        engaged — setup signals are OS-blocked) is instead handled at the
        loop's first synced boundary, with a fully-built trainer that can
        run the coordinated save.
        """
        if not self._sync_signals:
            self.signal_flag.check()

    # ------------------------------------------------------------------ run
    def run(self) -> None:
        cfg = self.cfg
        tokens_per_step = cfg.batch_size * cfg.sequence_length
        self._step_window_start = (time.time(), self.training_step - 1)
        if self._resumed:
            # ref: train.py:81
            events.emit_audit(
                logger, AUDIT_RESUME_FMT.format(step=self.training_step),
                "resume", step=self.training_step,
                tokens_per_step=tokens_per_step)
        else:
            # ref: train.py:84
            events.emit_audit(logger, AUDIT_START, "start", step=0,
                              tokens_per_step=tokens_per_step)
        if self._compile_event is not None:
            events.emit("compile", **self._compile_event)
            self._compile_event = None

        whole_run_trace = (cfg.profile_dir and not cfg.trace_steps
                           and self._auto_trace is None)
        if whole_run_trace:
            # bare --profile-dir keeps its whole-run capture; --trace-steps
            # and --auto-trace supersede it with a bounded window
            # (obs/trace.py) — one profiler owner at a time
            jax.profiler.start_trace(cfg.profile_dir)
        try:
            self._loop()
        except Exception as e:
            # A host-local fault must be announced AS THE EXCEPTION UNWINDS
            # (before the exit handler runs the fence): the peers' per-
            # dispatch poll sees the key within one iteration, bounding how
            # far ahead they dispatch. Agreed signals, replicated errors and
            # peer echoes are cluster-visible already.
            if (self._sync_signals and not self.error_is_replicated
                    and not isinstance(e, (TrainingSignal, PeerHostError))):
                multihost.announce_local_error(self._dispatched)
            raise
        finally:
            if whole_run_trace:
                jax.profiler.stop_trace()
            if self._trace is not None:
                self._trace.close()
            if self._auto_trace is not None:
                self._auto_trace.close()

    def _loop(self) -> None:
        cfg = self.cfg
        it = self._batch_iter = iter(self.prefetcher)
        sync_freq = max(1, cfg.signal_sync_frequency)
        first_iteration = True
        while self.training_step < cfg.training_steps:
            if self.chaos is not None:
                # Sync-boundary faults (kv_delay / kv_fail) fire BEFORE the
                # real agreement round below, modeling a slow or failed
                # KV-store round at the exact point one would hurt.
                self.chaos.on_sync_boundary(self, self.training_step)
            if self._sync_signals:
                # Host-side non-blocking poll FIRST: a peer's announced
                # local fault must stop this host before it dispatches
                # further steps the faulted peer will never join (pod fault
                # fence, ft/multihost.py). One KV round trip per iteration,
                # no device work, no drain.
                if multihost.peer_error_pending():
                    raise PeerHostError()
                # Cluster-wide signal agreement at sync boundaries, over
                # the KV store (ft/multihost.py agree_on_signal): pure
                # host-side gRPC — no device collective, so the dispatch
                # pipeline keeps flowing through the boundary, and a peer
                # that faults or dies mid-agreement cannot wedge this
                # host's device queue (review r5; the old allgather form
                # both forced a drain per boundary and could strand a
                # survivor's queued programs behind a dead collective).
                # Off-boundary local raises are still skipped — a host
                # raising alone would deadlock the others in their next
                # step collectives. The first iteration always syncs so a
                # signal pending since before setup (see _setup_check) is
                # handled immediately even when the resumed step is
                # off-boundary. Round ids advance identically on every
                # host: boundaries are a pure function of training_step.
                if first_iteration or self.training_step % sync_freq == 0:
                    self._signal_round += 1
                    verdict = multihost.agree_on_signal(
                        self.signal_flag.signum,
                        round_id=self._signal_round,
                        timeout_seconds=self.cfg.peer_timeout_seconds,
                        logger=logger)
                    if verdict is not None:
                        self.signal_flag.signum = None
                        raise TrainingSignal(verdict)
            else:
                self.signal_flag.check()
            first_iteration = False
            t_fetch = time.perf_counter()
            inputs, labels, data_state = next(it)
            # Data-stall accounting: with the prefetcher healthy this is
            # ~0; a growing counter at /metrics means the input pipeline,
            # not the TPU, is the bottleneck.
            self._m_stall.inc(time.perf_counter() - t_fetch)
            if self._trace is not None:
                self._trace.on_step_start(self.training_step)
            with (self._trace.annotate(self.training_step)
                  if self._trace is not None else contextlib.nullcontext()):
                self.state, metrics = self._compiled_step(self.state,
                                                          inputs, labels)
            self._dispatched += 1
            self._last_data_state = data_state
            # The jitted step pre-packs (loss, grad_norm) into one array so
            # _consume pays ONE host round trip per step, not one per metric
            # (each fetch is a full RPC on tunneled device transports).
            self._inflight.append((self.training_step, metrics["packed"]))
            while len(self._inflight) >= max(1, cfg.inflight):
                self._consume(*self._inflight.popleft())
            # Deterministic fault injection (ref: train.py:112-113): the
            # single training-loop injection site, fired while the counter
            # still equals the entry's step, after the update. The legacy
            # --raise-error flag is an alias for one 'exception' entry
            # (chaos/injector.py from_config); signal, exception and
            # checkpoint-corruption faults all originate here.
            if self.chaos is not None:
                self.chaos.on_train_step(self, self.training_step)
            if self._trace is not None:
                self._trace.on_step_end(self.training_step)
            self.training_step += 1
            if (cfg.checkpoint_frequency
                    and self.training_step % cfg.checkpoint_frequency == 0):
                # The FIRST periodic save blocks to measure the real
                # write wall against the signal lead (the startup budget
                # line only extrapolates a 128 MiB probe — ADVICE r3:
                # on filesystems with throughput cliffs the estimate is
                # optimistic and the operator must learn BEFORE the first
                # preemption, not during it). Later saves are async.
                first = not self._budget_observed
                self._budget_observed = True
                saved = self.save_checkpoint(wait=first, stop_prefetch=False)
                if self._publisher is not None:
                    # The pointer must never point at a step without its
                    # integrity manifest (the watcher would reject it), so
                    # an async save drains before publishing. That trades
                    # the async overlap for a durable deployment point —
                    # the cadence that wants both is a higher
                    # --checkpoint-frequency, not a torn publish.
                    self.ckpt_mngr.wait_until_finished()
                    self._publisher.publish(saved)
            if (self._compiled_eval is not None
                    and self.training_step % cfg.eval_frequency == 0):
                self._evaluate()
        self._drain_inflight()
        self._emit_tail_window()
        if (self._compiled_eval is not None
                and self.training_step % cfg.eval_frequency != 0):
            self._evaluate()  # final eval unless the last step just ran one

    def _emit_tail_window(self) -> None:
        """Close the step-window accounting. Steps drained with
        ``check=False`` (pre-save drains) skip metric consumption by design,
        so a run whose last act was a periodic save would leave its final
        window unrecorded — the goodput stitcher would count those steps'
        wall as lost. One synthetic window event covers the gap."""
        if self._step_window_start is None:
            return
        prev_t, prev_step = self._step_window_start
        last = self.training_step - 1
        if last <= prev_step:
            return
        now_wall = time.time()
        n = last - prev_step
        events.emit(kind="step", step=last, dur=now_wall - prev_t, steps=n,
                    tokens=n * self.throughput.tokens_per_step, tail=True)
        self._step_window_start = (now_wall, last)

    def _evaluate(self) -> None:
        """One held-out pass: ``--eval-batches`` batches, token-weighted mean
        NLL + perplexity. The eval set is fixed and rewound each pass, so
        evaluation is deterministic, independent of the training data
        position, and adds no checkpoint state; its tokenized batches are
        cached after the first pass, and all forward calls are dispatched
        before any result is fetched (no host/device serialization)."""
        if self._eval_batches_cache is None:
            self.eval_loader.set_state({"kind": "map", "next_index": 0})
            self._eval_batches_cache = list(self.eval_loader)
        packed = []
        for inputs, labels in self._eval_batches_cache:
            inputs = jax.device_put(inputs, self.batch_sharding)
            labels = jax.device_put(labels, self.batch_sharding)
            packed.append(self._compiled_eval(self.state.params, inputs,
                                              labels))
        t0 = time.perf_counter()
        totals = np.sum([np.asarray(p) for p in packed], axis=0)
        loss = float(totals[0]) / max(float(totals[1]), 1.0)
        ppl = math.exp(min(loss, 700.0))
        self._m_eval_loss.set(loss)
        logger.info(f"Eval | step {self.training_step} | loss {loss:.4f} | "
                    f"ppl {ppl:.2f} | tokens {int(totals[1])}")
        events.emit(kind="eval", step=self.training_step,
                    dur=time.perf_counter() - t0, loss=loss, ppl=ppl,
                    tokens=int(totals[1]))

    def _drain_inflight(self, check: bool = True, cancelled=None) -> None:
        """Consume every dispatched-but-unfinished step.

        Must run before ANY host-thread collective (signal agreement,
        pre-save barrier): a dispatched step's collectives execute on
        runtime threads, and a collective issued concurrently from the host
        thread can interleave in different orders on different hosts
        (observed as a gloo payload-size mismatch on multi-process CPU
        runs). With the pipeline empty the host's collective is the only
        one in flight anywhere.

        ``check=False`` (exit-handler saves): wait for completion but skip
        the metric consumption — after a fault the remaining steps' metrics
        may be non-finite too, and re-raising inside the save would abort
        the checkpoint the handler exists to write.

        ``cancelled`` (watchdog runs): once set, this thread has been
        abandoned by its watchdog — stop touching the shared deque and
        issue nothing further; the fence owns the drain from here."""
        while self._inflight:
            if cancelled is not None and cancelled.is_set():
                return
            step_no, packed = self._inflight.popleft()
            if check:
                self._consume(step_no, packed)
            else:
                np.asarray(packed)  # completion only

    def _guarded_wait(self, fn, what: str):
        """Run a blocking multihost wait under the fence watchdog
        (ft/multihost.py). On timeout: a pending peer-fault announcement
        means the peer stopped dispatching on purpose — raise
        ``PeerHostError`` so the exit handler runs the fence and the
        coordinated save; no announcement means the peer is dead (SIGKILL,
        node loss) — degrade to a clean no-save exit instead of hanging
        until the scheduler shoots this host too. Single-process (and
        re-entrant) calls run ``fn`` directly. Runs on the persistent
        waiter — this is the per-step path, and a fresh watchdog thread
        per step is pure churn."""
        if not self._sync_signals or self._in_guard:
            return fn(_NEVER_CANCELLED)  # direct execution
        self._in_guard = True
        try:
            ok, result = self._waiter.run(fn,
                                          self.cfg.peer_timeout_seconds)
        finally:
            self._in_guard = False
        if ok:
            return result
        # After the fence the err keys are stale (every host is already in
        # its exit handler) — a timeout there means a peer died mid-save;
        # re-raising inside the exit handler would break the exit-0
        # contract, so degrade instead.
        if (not self._fence_done and multihost.peer_error_pending()
                and not multihost.peer_dead_pending()):
            raise PeerHostError()
        multihost.die_uncoordinated(
            logger, f"{what} exceeded --peer-timeout-seconds "
                    f"{self.cfg.peer_timeout_seconds:g} with no live peer")

    def _consume(self, step_no: int, packed: jnp.ndarray) -> None:
        """Pull one step's packed (loss, grad_norm) to the host — the only
        D2H sync point (the reference syncs via loss.item() at
        train.py:116), and a single transfer. On a pod the wait is
        watchdogged: a step whose collectives a faulted peer never joined
        would otherwise block forever (the finiteness check of a step
        abandoned this way is skipped — the run is ending either way)."""
        vals = self._guarded_wait(lambda _cancelled: np.asarray(packed),
                                  f"metric wait for step {step_no}")
        loss, grad_norm = float(vals[0]), float(vals[1])
        if not math.isfinite(grad_norm):
            # ref: utils.py:61 error_if_nonfinite -> routed as code error (-1)
            # grad_norm is a replicated global value: every host raises here
            self.error_is_replicated = True
            raise NonFiniteGradientError(
                f"non-finite gradient norm {grad_norm} at step {step_no}")
        self.throughput.step()
        now = time.perf_counter()
        if self._last_consume_t is not None:
            dt = now - self._last_consume_t
            self._m_step_time.observe(dt)
            if self._auto_trace is not None:
                ratio = self._auto_trace.observe(step_no, dt)
                if ratio is not None:
                    events.emit_audit(
                        logger,
                        AUDIT_TRACE_AUTO_FMT.format(ratio=ratio,
                                                    step=step_no),
                        "trace_auto", step=step_no, ratio=ratio,
                        trace_dir=self._auto_trace.trace_dir)
        self._last_consume_t = now
        self.last_loss = loss
        self._m_loss.set(loss)
        self._m_gnorm.set(grad_norm)
        self._m_stepg.set(step_no)
        self._m_tokens.inc(self.throughput.tokens_per_step)
        if step_no == 1 or step_no % self.cfg.logging_frequency == 0:
            # ref: train.py:115-116 (exact format), plus throughput extras.
            # The audit string stays byte-identical; the paired event
            # carries the window accounting goodput stitching needs.
            prev_t, prev_step = (self._step_window_start
                                 or (time.time(), step_no - 1))
            steps_in_window = max(1, step_no - prev_step)
            now_wall = time.time()
            events.emit_audit(
                logger, AUDIT_STEP_FMT.format(step=step_no,
                                              loss=self.last_loss),
                "step", step=step_no, dur=now_wall - prev_t,
                steps=steps_in_window,
                tokens=steps_in_window * self.throughput.tokens_per_step,
                loss=loss, grad_norm=grad_norm)
            self._step_window_start = (now_wall, step_no)
            # Staleness gauge ages on the logging cadence; save/restore
            # reset it to 0 (checkpoint/manager.py).
            update_checkpoint_age_gauge()
            tps = self.throughput.tokens_per_sec
            if tps:
                window = self.throughput.window_tag or "steady"
                self._m_tps.labels(window=window).set(tps)
                peak = device_peak_flops()
                if peak:
                    self._m_mfu.set(mfu(tps / max(jax.process_count(), 1)
                                        / max(jax.local_device_count(), 1),
                                        self._flops_per_token, peak))
                for dev, used, limit in per_device_memory_stats():
                    self._m_hbm_used.labels(device=dev).set(used)
                    if limit:
                        self._m_hbm_limit.labels(device=dev).set(limit)
                hbm = hbm_usage_str()
                logger.info(
                    f"Metrics | step {step_no} | grad_norm "
                    f"{grad_norm:.3f} | tokens/s {tps:,.0f}"
                    + (f" | hbm {hbm}" if hbm else "")
                    + (" | window post_resume"
                       if self.throughput.window_tag else ""))
                if self.throughput.window_tag:
                    # the transient window has now been reported once,
                    # tagged; subsequent windows are steady-state again
                    self.throughput.clear_tag()

    # ---------------------------------------------------------- fault fence
    def coordinate_local_error(self) -> bool:
        """Pod fault fence (ft/multihost.py module docstring): converge
        every host on the cluster-maximum dispatched step so the exit
        handler's −1 save can run *coordinated* — the reference's "always
        save on error" guarantee (ref: utils.py:69-81) at pod scale.

        Returns True when converged (the caller then runs the coordinated
        save). On an unreachable peer it does not return: the degraded
        path logs and exits 0 without a checkpoint. Single-process:
        trivially True."""
        if not self._sync_signals:
            return True
        timeout = self.cfg.peer_timeout_seconds
        multihost.publish_stop(self._dispatched)
        # 2x: a peer can spend one full watchdog period blocked in a device
        # wait before its own timeout routes it here to publish its stop.
        stops = multihost.gather_stops(2 * timeout)
        if stops is None:
            multihost.die_uncoordinated(
                logger, "a peer never published its stop step")
        target = max(stops.values())
        if self._dispatched < target:
            logger.info(f"Fault fence: catching up from dispatched step "
                        f"{self._dispatched} to agreed step {target}")
            try:
                self._catch_up_to(target)
            except Exception:
                logger.exception("Fault fence: catch-up failed")
                multihost.publish_dead()
                multihost.die_uncoordinated(
                    logger, f"cannot reach agreed step {target}")
        # poll=peer_dead_pending: a host that declared itself unable to
        # catch up will never complete these steps — degrade within the
        # poll interval instead of burning the whole timeout.
        ok, _ = multihost.watchdog(
            lambda c: self._drain_inflight(check=False, cancelled=c),
            timeout, poll=multihost.peer_dead_pending)
        if not ok:
            multihost.die_uncoordinated(
                logger, "peer unresponsive while draining at the fence")
        self._fence_done = True
        return True

    def _catch_up_to(self, target: int) -> None:
        """Dispatch real steps until this host reaches the fence's agreed
        step. Every host dispatched at most ``target`` programs, so each
        catch-up step completes the peers' already-pending collectives —
        no garbage data, no divergence: the saved state is the one an
        uninterrupted run would have produced."""
        it = self._batch_iter
        if it is None:
            it = self._batch_iter = iter(self.prefetcher)
        while self._dispatched < target:
            inputs, labels, data_state = next(it)
            self.state, metrics = self._compiled_step(self.state, inputs,
                                                      labels)
            self._dispatched += 1
            self.training_step = self._dispatched
            self._last_data_state = data_state
            self._inflight.append((self._dispatched - 1, metrics["packed"]))

    # --------------------------------------------------------------- saving
    def save_checkpoint(self, wait: bool = True,
                        stop_prefetch: bool = True,
                        coordinated: bool = True,
                        fault: bool = False) -> int:
        """Checkpoint the state of every *dispatched* step plus the matching
        data position. All dispatched XLA work completes by construction, so
        zero steps are lost (the reference's guarantee: saved @427, resumed
        @427 — BASELINE.md).

        ``coordinated=False`` (exit handler, error of unknown provenance)
        skips the pre-save barrier — on a pod the other hosts may still be
        stepping and would never reach it."""
        if stop_prefetch:
            self.prefetcher.stop()
        if coordinated:
            # The barrier is a host-thread collective: the dispatch pipeline
            # must be empty first (see _drain_inflight). No-op when the
            # caller (signal check, injection, loop end) already drained;
            # check=False so a post-fault save cannot re-raise on the
            # remaining steps' (possibly also non-finite) metrics. On a pod
            # the whole sequence is watchdogged: a peer dying between the
            # fence and here must not hang the save forever.
            def _pre_save(cancelled):
                self._drain_inflight(check=False, cancelled=cancelled)
                if cancelled.is_set():
                    return  # abandoned: no fresh collectives
                barrier("ftl:pre-save")  # all hosts drained, same step

            self._guarded_wait(_pre_save, "pre-save drain/barrier")
        step = int(jax.device_get(self.state.step))
        data_state = self._last_data_state or self.loader.get_state()
        if self._sync_signals and wait:
            # The sharded write is itself a cross-host collective — a peer
            # dying mid-write must not hang the survivors until the
            # scheduler shoots them (that would break the exit-0
            # never-mark-failed contract). FAULT-path bound: the larger of
            # the peer watchdog and 2x the signal lead (a fault save
            # slower than the lead is lost to the scheduler anyway).
            # HEALTHY blocking saves (the first periodic write, which
            # exists to measure the real filesystem) get a bound scaled to
            # the startup write-probe estimate with a 10x margin — a slow
            # but live filesystem warns, only a genuinely wedged
            # collective degrades (review r5, both directions). Orbax's
            # atomic commit makes an abandoned partial write invisible.
            bound = max(self.cfg.peer_timeout_seconds,
                        2.0 * self.cfg.signal_lead_seconds)
            if not fault:
                est = self._est_save_seconds
                bound = max(bound, 10.0 * est if est else 3600.0, 600.0)
            ok, _ = multihost.watchdog(
                lambda _c: self.ckpt_mngr.save(step, self.state, data_state,
                                               wait=True), bound)
            if not ok:
                multihost.die_uncoordinated(
                    logger, "collective checkpoint write stalled")
        else:
            self.ckpt_mngr.save(step, self.state, data_state, wait=wait)
        self._m_saves.inc()
        if wait and self.ckpt_mngr.last_save_seconds is not None:
            self._m_save.observe(self.ckpt_mngr.last_save_seconds)
        events.emit(kind="ckpt_save", step=step,
                    dur=(self.ckpt_mngr.last_save_seconds
                         if wait else None),
                    blocking=bool(wait), fault=bool(fault))
        if wait and self.ckpt_mngr.last_save_seconds is not None:
            # observed wall for blocking (fault-path) saves: the number the
            # startup budget estimate exists to predict
            from ..checkpoint.manager import state_bytes

            secs = self.ckpt_mngr.last_save_seconds
            total = state_bytes(self.state)
            logger.info(f"Checkpoint write | {total / 1e9:.2f} GB in "
                        f"{secs:.1f} s ({total / 1e9 / max(secs, 1e-6):.2f} "
                        f"GB/s)")
            # Re-check the budget against OBSERVED reality (ADVICE r3):
            # the startup estimate extrapolates a 128 MiB probe, which can
            # be optimistic on network filesystems with throughput cliffs
            # at multi-GB writes or uneven host shards. A measured save
            # that blows the lead is the ground truth the warning exists
            # for.
            lead = self.cfg.signal_lead_seconds
            if secs > lead:
                logger.warning(
                    f"Checkpoint budget EXCEEDED (observed): this save took "
                    f"{secs:.0f} s > the {lead} s signal lead — the startup "
                    f"estimate was optimistic for this filesystem; a "
                    f"preemption may outrun the save.")
        return step

    def close(self) -> None:
        self.prefetcher.stop()
        self.ckpt_mngr.close()
        if self._trace is not None:
            self._trace.close()
        if self._auto_trace is not None:
            self._auto_trace.close()
        if self._heartbeat is not None:
            self._heartbeat.stop()
            self._heartbeat = None
        if self._metrics_server is not None:
            self._metrics_server.stop()
            self._metrics_server = None
        events.flush()
        if self._mesh_ctx is not None:
            self._mesh_ctx.__exit__(None, None, None)
            self._mesh_ctx = None
