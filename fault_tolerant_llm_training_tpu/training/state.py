"""Training state pytree.

The reference's equivalent is the checkpoint dict
``{model, optimizer, lr_scheduler, training_step}`` (ref: utils.py:75-80) —
here it is a single immutable pytree threaded through the jitted step. The LR
scheduler needs no separate state: the optax schedule is a pure function of
the optimizer's update count.
"""

from typing import Any

import jax
from flax import struct


class TrainState(struct.PyTreeNode):
    step: jax.Array  # int32 scalar; ref 'training_step' (utils.py:79)
    params: Any  # ref 'model' state_dict
    opt_state: Any  # ref 'optimizer' (+ the schedule count = 'lr_scheduler')
