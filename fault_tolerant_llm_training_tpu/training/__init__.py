from .state import TrainState
from .step import cross_entropy_loss, make_optimizer, make_train_step
from .loop import Trainer

__all__ = ["TrainState", "cross_entropy_loss", "make_optimizer",
           "make_train_step", "Trainer"]
