"""Sharding rules: logical axes -> mesh axes, and param-path -> logical axes.

This is the build's FSDP/TP layer (SURVEY.md §2.3: the reference has none; the
BASELINE.json north star requires DP psum + pjit/NamedSharding FSDP). Instead
of boxing Flax params in metadata, shardings are derived from the parameter
tree *path* with regex rules — transparent, testable, and Orbax-friendly.

Logical activation/parameter axes:

- batch -> ('data', 'fsdp')   (FSDP also shards the batch)
- seq   -> 'sequence'         (ring attention shards)
- vocab -> 'tensor'
- embed -> 'fsdp'             (FSDP shards params along their embed dim)
- heads -> 'tensor'           (Megatron: split attention heads)
- mlp   -> 'tensor'           (Megatron: split SwiGLU hidden)
- norm  -> None               (tiny vectors, replicated)

With this single rule set, FSDP-only meshes (tp=1) shard every matrix over
'fsdp' on its embed dim, TP-only meshes split heads/mlp/vocab, and combined
meshes do both — XLA inserts all-gathers / reduce-scatters / psums from the
NamedShardings (the scaling-book recipe).
"""

import contextlib
import re
from typing import Dict, Optional, Sequence, Tuple

import jax
from jax.sharding import NamedSharding, PartitionSpec as P

from .mesh import active_mesh

_CONSTRAINTS_SUSPENDED = False


@contextlib.contextmanager
def suspend_constraints():
    """Disable ``constrain`` for the dynamic extent of a trace.

    Needed while tracing the pipeline's partial-manual shard_map body
    (parallel/pipeline.py): inside ``lax.scan`` the Manual-context query
    below is unreliable, and a constraint stamped on the all-auto mesh
    inside the manual region breaks the shard_map transpose."""
    global _CONSTRAINTS_SUSPENDED
    prev = _CONSTRAINTS_SUSPENDED
    _CONSTRAINTS_SUSPENDED = True
    try:
        yield
    finally:
        _CONSTRAINTS_SUSPENDED = prev

LOGICAL_RULES: Dict[str, object] = {
    "batch": ("data", "fsdp"),
    "seq": "sequence",
    # vocab shards over pipe AND tensor: on a pp mesh every stage stores
    # only its vocab slice of the embed table / head weight and computes
    # only its slice of the (B, S, V) logits — one head matmul total
    # across the mesh instead of P replicated ones (the round-1 pipeline
    # recomputed the model's largest matmul on every stage). The CE is
    # gather-free (training/step.py) so vocab-sharded logits reduce with
    # small (B, S) collectives, never an all-gather of logits. 'pipe'
    # MAJOR: the 1F1B pipeline's in-loop head (parallel/pipeline.py) views
    # the weight as (D, P, V/P) under a partial-manual shard_map, which is
    # a reshard-free reshape only when each stage's slice is contiguous
    # (pipe outermost); the tensor sub-sharding stays inside each slice.
    "vocab": ("pipe", "tensor"),
    "embed": "fsdp",
    # activations keep their feature dim replicated (FSDP shards params, not
    # activations; 'embed' -> fsdp applies to parameter matrices only)
    "act_embed": None,
    "heads": "tensor",
    "kv_heads": "tensor",
    "mlp": "tensor",
    "norm": None,
    # leading layer-stack axis of scan-form params (models/llama.py
    # layer_impl="scan"): sharded by pipeline stage, so each stage stores
    # only its own layers (parallel/pipeline.py); on meshes without a pipe
    # axis (size 1) this resolves to replicated
    "layers": "pipe",
    # leading expert axis of MoE expert stacks and activations
    # (models/moe.py): each device on the 'expert' axis stores and computes
    # only its experts; XLA inserts the dispatch/combine all-to-all
    "expert_stack": "expert",
}

# Parameter-path (joined with '/') -> logical axes of that parameter.
PARAM_AXIS_RULES: Sequence[Tuple[str, Tuple[Optional[str], ...]]] = (
    (r"tok_embeddings/embedding$", ("vocab", "embed")),
    (r"wq/kernel$", ("embed", "heads")),
    (r"wk/kernel$", ("embed", "kv_heads")),
    (r"wv/kernel$", ("embed", "kv_heads")),
    (r"wo/kernel$", ("heads", "embed")),
    (r"w1/kernel$", ("embed", "mlp")),
    (r"w3/kernel$", ("embed", "mlp")),
    (r"w2/kernel$", ("mlp", "embed")),
    (r"output/kernel$", ("embed", "vocab")),
    (r"router/kernel$", ("embed", None)),  # MoE router (models/moe.py)
    (r"(scale|norm)[^/]*$", ("norm",)),
)


def _resolve(logical_axes, rules=None) -> P:
    rules = LOGICAL_RULES if rules is None else rules
    return P(*(rules.get(a) if a is not None else None for a in logical_axes))


_FIT_WARNED = set()


def _fit_spec(spec: P, shape, mesh) -> P:
    """Drop mesh axes a dimension cannot actually be sharded over.

    An indivisible dim (e.g. the byte tokenizer's 259-entry vocab over a
    ('pipe', 'tensor') product) would be a hard pjit error; degrading that
    dim to the divisible prefix of its axes (possibly replicated) is always
    semantically valid — the same per-axis degrade the ring attention op
    applies to its batch axes. Dropping an axis on a non-trivial dim is
    logged once per (dim, axes) pair: silent replication of a large param
    or batch is a real capacity/compute cost the operator should see."""
    if mesh is None:
        return spec
    fitted = []
    for dim, axes in zip(shape, tuple(spec) + (None,) * (len(shape) - len(spec))):
        if axes is None:
            fitted.append(None)
            continue
        keep, dropped, prod = [], [], 1
        for a in axes if isinstance(axes, tuple) else (axes,):
            n = mesh.shape.get(a, 1)
            if dim % (prod * n) == 0:
                keep.append(a)
                prod *= n
            elif n > 1:
                dropped.append(a)
        if dropped and dim >= 64 and (dim, tuple(dropped)) not in _FIT_WARNED:
            _FIT_WARNED.add((dim, tuple(dropped)))
            import logging
            logging.getLogger(__name__).warning(
                "sharding: dim %d is not divisible by mesh axes %s "
                "(sizes %s); that dim degrades to %s — replicated work/"
                "storage where sharding was requested",
                dim, dropped, [mesh.shape.get(a, 1) for a in dropped],
                keep or "replicated")
        fitted.append(tuple(keep) if len(keep) > 1
                      else (keep[0] if keep else None))
    return P(*fitted)


def shard_size(dim: int, logical_axis: str, mesh=None) -> int:
    """How many ways ``dim`` would actually shard over ``logical_axis`` on
    the active mesh, after the :func:`_fit_spec` divisibility degrade.

    The dispatch predicate for layout-sensitive implementation choices
    (e.g. embed gather-vs-one_hot, dense-vs-blocked CE): axis size alone
    lies when the dim is indivisible and silently degrades to replication.
    """
    mesh = mesh or active_mesh()
    if mesh is None:
        return 1
    spec = _fit_spec(_resolve((logical_axis,)), (dim,), mesh)
    axes = spec[0]
    if axes is None:
        return 1
    prod = 1
    for a in (axes if isinstance(axes, tuple) else (axes,)):
        prod *= mesh.shape.get(a, 1)
    return prod


def logical_pspec(*logical_axes) -> P:
    return _resolve(logical_axes)


def vocab_shard_axes(w_shape, mesh) -> Tuple[str, ...]:
    """Mesh axes that actually shard the vocab dim of a (D, V) weight on
    ``mesh`` (after the :func:`_fit_spec` divisibility degrade), in
    sharding-major order. The single source of truth for every consumer
    that hand-schedules over the vocab sharding (the fused sharded CE in
    ops/fused_ce.py and the 1F1B pipeline's in-loop head) — their offset
    math must agree or labels land in the wrong shard."""
    fitted = _fit_spec(logical_pspec("embed", "vocab"), w_shape, mesh)
    axes = fitted[1]
    return axes if isinstance(axes, tuple) else ((axes,) if axes else ())


def batch_pspec() -> P:
    """Batches: (B, S) sharded batch->data+fsdp, seq->sequence."""
    return _resolve(("batch", "seq"))


def constrain(x: jax.Array, *logical_axes) -> jax.Array:
    """``with_sharding_constraint`` against the active mesh; no-op without one.

    Axes whose mesh axis has size 1 still resolve fine (XLA treats them as
    unsharded), so the same model code traces identically on a laptop CPU and
    a v5p-64 mesh. Inside a partial-manual ``shard_map`` (the pipeline
    trunk, parallel/pipeline.py) the constraint must be built on the
    context's abstract mesh — whose manual axes (e.g. 'pipe') may not be
    referenced — not on the all-auto concrete mesh."""
    mesh = active_mesh()
    if mesh is None or len(logical_axes) != x.ndim or _CONSTRAINTS_SUSPENDED:
        return x
    # get_abstract_mesh landed after 0.4.x; without it there is no
    # Manual-context introspection (and no partial-manual tracing either),
    # so the constraint is always safe to emit.
    _get_abstract = getattr(jax.sharding, "get_abstract_mesh", None)
    abstract = _get_abstract() if _get_abstract is not None else None
    if abstract is not None and getattr(abstract, "shape_tuple", ()):
        if any(str(kind) == "Manual" for kind in abstract.axis_types):
            # Inside a partial-manual shard_map (the pipeline trunk,
            # parallel/pipeline.py) constraints built on the all-auto
            # concrete mesh clash with the Manual context (and rebuilt ones
            # still break under autodiff replay); the auto axes' shardings
            # propagate from the body's inputs, so skip the hint here.
            return x
    spec = _fit_spec(_resolve(logical_axes), x.shape, mesh)
    return jax.lax.with_sharding_constraint(x, NamedSharding(mesh, spec))


def param_pspecs(params) -> dict:
    """PartitionSpec pytree for a param pytree, from PARAM_AXIS_RULES paths."""

    def spec_for(path: str, leaf) -> P:
        for pattern, axes in PARAM_AXIS_RULES:
            if re.search(pattern, path):
                axes = tuple(axes)
                # stacked-param prefixes, outermost first: the scan-form
                # layer axis, then the MoE expert axis (both optional)
                if re.search(r"(^|/)experts/", path) and leaf.ndim > len(axes):
                    axes = ("expert_stack",) + axes
                if (re.search(r"(^|/)layers/block/", path)
                        and leaf.ndim > len(axes)):
                    axes = ("layers",) + axes
                if len(axes) != leaf.ndim:
                    raise ValueError(
                        f"rule {pattern!r} gives {len(axes)} axes for {path} "
                        f"with ndim {leaf.ndim}")
                return _fit_spec(_resolve(axes), leaf.shape, active_mesh())
        return P(*([None] * leaf.ndim))  # replicate unknown params

    flat = jax.tree_util.tree_flatten_with_path(params)
    specs = {}
    for keypath, leaf in flat[0]:
        path = "/".join(_key_str(k) for k in keypath)
        specs[path] = spec_for(path, leaf)
    return jax.tree_util.tree_unflatten(
        flat[1], [specs["/".join(_key_str(k) for k in kp)] for kp, _ in flat[0]])


def param_shardings(params, mesh=None):
    """NamedSharding pytree for ``params`` on ``mesh`` (default: active mesh)."""
    mesh = mesh or active_mesh()
    if mesh is None:
        return None
    return jax.tree_util.tree_map(
        lambda spec: NamedSharding(mesh, spec), param_pspecs(params),
        is_leaf=lambda x: isinstance(x, P))


def _key_str(k) -> str:
    if hasattr(k, "key"):  # DictKey
        return str(k.key)
    if hasattr(k, "name"):  # GetAttrKey (e.g. TrainState fields)
        return str(k.name)
    if hasattr(k, "idx"):  # SequenceKey (e.g. optax chain tuples)
        return str(k.idx)
    return str(k)
