"""Pipeline parallelism: GPipe over the mesh's ``pipe`` axis.

No reference counterpart (SURVEY.md §2.3: the reference has no parallelism
at all) — this is a beyond-parity scale-out path completing the mesh
portfolio (dp / pp / fsdp / sp / tp). TPU-native design:

- layer-stacked (scan-form) params are sharded over ``pipe`` on their
  leading layer axis by the path rules (parallel/sharding.py), so stage
  ``s`` *stores* only layers ``[s*L/P, (s+1)*L/P)`` — the memory win that
  motivates PP;
- the trunk runs under a partial-manual ``shard_map`` (``axis_names=
  {'pipe'}``): the pipe axis is hand-scheduled while data/fsdp/tensor
  shardings stay with the auto partitioner, so PP composes with DP/FSDP/TP
  without manual collectives for them;
- microbatches flow stage-to-stage via ``lax.ppermute`` in a GPipe
  schedule of ``M + P - 1`` ticks (bubble fraction (P-1)/(M+P-1));
  autodiff through the schedule yields the reverse pipeline for free;
- embedding and head run *outside* the shard_map under the auto
  partitioner, with the vocab axis sharded over ``('tensor', 'pipe')``
  (parallel/sharding.py): every stage stores only its vocab slice of the
  embed table / head weight and computes only its slice of the (B, S, V)
  head matmul — one head matmul total across the mesh, reduced by the
  gather-free CE (training/step.py) with small (B, S) collectives.

The jitted result computes exactly the same function as the plain trunk
(tests/test_pipeline.py pins loss equivalence on the CPU mesh).
"""

import jax
import jax.numpy as jnp
from jax import shard_map
from jax.sharding import PartitionSpec as P

from ..parallel.mesh import active_mesh


def pipeline_hidden(model, params, x, positions, mesh=None,
                    microbatches: int = 0) -> jax.Array:
    """Run the scan-form trunk through the GPipe schedule.

    ``x``: (B, S, D) embedded activations (global view); returns the final
    hidden states (B, S, D). Caller applies embed before and head after.
    """
    from ..models.llama import TransformerBlock

    mesh = mesh or active_mesh()
    pp = mesh.shape["pipe"]
    n_micro = microbatches or pp
    cfg = model.cfg
    if cfg.n_layers % pp:
        raise ValueError(f"n_layers {cfg.n_layers} not divisible by pp {pp}")
    if x.shape[0] % n_micro:
        raise ValueError(
            f"batch {x.shape[0]} not divisible by microbatches {n_micro}")

    from flax import linen as nn

    block_cls = TransformerBlock
    if cfg.remat:
        block_cls = nn.remat(TransformerBlock, prevent_cse=False,
                             static_argnums=())
    block = block_cls(cfg)
    stacked = params["layers"]["block"]

    def local_layers(stack_local, h, pos):
        def step(c, layer_params):
            return block.apply({"params": layer_params}, c, pos), None
        out, _ = jax.lax.scan(step, h, stack_local)
        return out

    compute_dtype = x.dtype
    b, seq, d = x.shape
    mb = b // n_micro

    # Split into microbatches OUTSIDE the manual region, pad with the pp-1
    # drain ticks, and pin the sharding explicitly: the scan below then
    # consumes its xs natively (no dynamic_index over an axis the reshape
    # silently left batch-sharded — that indexing forced the partitioner
    # into an involuntary full rematerialization per tick). The constraint
    # puts the batch sharding on the per-microbatch batch dim when it
    # divides, and degrades to explicit (voluntary) replication when it
    # does not (tiny dryrun shapes).
    from ..parallel.sharding import constrain, suspend_constraints
    micro = x.astype(jnp.float32).reshape(n_micro, mb, seq, d)
    micro = jnp.concatenate(
        [micro, jnp.zeros((pp - 1, mb, seq, d), jnp.float32)], axis=0)
    micro = constrain(micro, None, "batch", None, None)

    def body(stack_local, micro, pos):
        s = jax.lax.axis_index("pipe")
        # boundary values travel in fp32: the cotangent of a replicated
        # (P()) shard_map input is accumulated with a psum over 'pipe', and
        # bf16 psums inside a partial-manual shard_map trip an XLA
        # partitioner CHECK (jax 0.9 / XLA CPU) — compute stays bf16
        micro = micro.astype(compute_dtype)
        ring = [(i, (i + 1) % pp) for i in range(pp)]

        # One lax.scan over the ticks (not an unrolled Python loop): the
        # layer scan inside is traced once, keeping compile time O(1) in
        # microbatches — the same reason the trunk itself is scanned.
        # Stage 0 injects microbatch t at tick t; stage P-1 emits finished
        # microbatch t-P+1, so the stacked ys hold them from tick P-1 on.
        def tick(recv, x_t):
            xin = jnp.where(s == 0, x_t, recv)
            out = local_layers(stack_local, xin, pos)
            recv = jax.lax.ppermute(out, "pipe", ring)
            return recv, out

        recv = jnp.zeros((mb, seq, d), compute_dtype)
        _, outs = jax.lax.scan(tick, recv, micro)
        outs = outs[pp - 1:]  # (n_micro, mb, seq, d), static slice
        outs = jnp.where(s == pp - 1, outs, jnp.zeros((), compute_dtype))
        # broadcast the last stage's result to every stage; fp32 for the
        # same partitioner reason as above, and it doubles as the fp32
        # boundary on the way out
        outs = jax.lax.psum(outs.astype(jnp.float32), "pipe")
        return outs.reshape(b, seq, d)

    stack_specs = jax.tree_util.tree_map(
        lambda leaf: P("pipe"), stacked)
    fn = shard_map(body, mesh=mesh,
                   in_specs=(stack_specs, P(), P()),
                   out_specs=P(), axis_names={"pipe"}, check_vma=False)
    with suspend_constraints():
        # constraints inside the manual region would stamp all-auto-mesh
        # shardings that break the shard_map transpose (see sharding.py)
        hidden = fn(stacked, micro, positions)
    return hidden.astype(x.dtype)


def pipeline_apply(model, params, tokens, mesh=None,
                   microbatches: int = 0) -> jax.Array:
    """Full forward (embed -> pipelined trunk -> head) -> logits."""
    x = model.apply({"params": params}, tokens, method="embed")
    positions = model.default_positions(tokens.shape[1])
    hidden = pipeline_hidden(model, params, x, positions, mesh=mesh,
                             microbatches=microbatches)
    return model.apply({"params": params}, hidden, method="head")
