"""Pipeline parallelism over the mesh's ``pipe`` axis: 1F1B and GPipe.

No reference counterpart (SURVEY.md §2.3: the reference has no parallelism
at all) — this is a beyond-parity scale-out path completing the mesh
portfolio (dp / pp / fsdp / sp / tp). TPU-native design shared by both
schedules:

- layer-stacked (scan-form) params are sharded over ``pipe`` on their
  leading layer axis by the path rules (parallel/sharding.py), so stage
  ``s`` *stores* only layers ``[s*L/P, (s+1)*L/P)`` — the memory win that
  motivates PP;
- the trunk runs under a partial-manual ``shard_map`` (``axis_names=
  {'pipe'}``): the pipe axis is hand-scheduled while data/fsdp/tensor
  shardings stay with the auto partitioner, so PP composes with DP/FSDP/TP
  without manual collectives for them;
- microbatches flow stage-to-stage via ``lax.ppermute``; the vocab axis
  shards over ``('pipe', 'tensor')`` (parallel/sharding.py) so every stage
  stores only its slice of the embed table / head weight and computes only
  its slice of any (.., S, V) logits — one head matmul total across the
  mesh.

Two schedules:

**1F1B** (:func:`pipeline_value_and_grad`, the training default): one
combined forward+backward tick loop of ``M + 2P - 1`` ticks. The head+CE
for microbatch ``m`` runs *inside* the loop the moment ``m``'s forward
leaves the last stage (a vocab-sharded online-softmax whose (m, l, picked)
stats merge with small (mb, S) psums over 'pipe' — the same algebra as
ops/fused_ce.py, which it reuses), so ``m``'s backward starts ``P`` ticks
later while later microbatches are still in forward flight. Consequences:

- trunk activation memory is O(P) in microbatches: each stage stashes at
  most ``2P-1`` microbatch *inputs* (a ring buffer) and recomputes its
  block internals during the backward tick (full-stage rematerialization
  — the same fwd+bwd work as GPipe-with-remat, ~4/3 the FLOPs of
  GPipe-without-remat), instead of the GPipe schedule's autodiff storing
  all ``M+P-1`` ticks of residuals. (The embed boundary and its
  cotangent remain O(B) full-batch buffers — they exist under any
  schedule, since embed and its backward run out-of-line.);
- logits exist only per-microbatch and per-vocab-shard: (mb, S, block)
  fp32 transients instead of the (B, S, V/P) fp32 tensor the out-of-line
  head materializes — at the reference's 131k vocab this is the larger win;
- gradients are assembled *explicitly* (the tick loop is never
  differentiated): stage-local layer grads accumulate in fp32 carries and
  leave sharded over 'pipe'; the boundary activations travel bf16 through
  the ppermutes (only psums are fp32 — bf16 psum trips an XLA partitioner
  CHECK, ROUND_NOTES.md);
- MoE router aux losses ride along naturally: each stage's forward tick
  accumulates its layers' sown aux (weighted by the microbatch's valid
  tokens — exactly the grad-accum semantics of training/step.py), and the
  backward tick's VJP carries the constant aux cotangent, so pp composes
  with MoE/ep.

**GPipe** (:func:`pipeline_hidden` / :func:`pipeline_apply`): the forward
tick scan of ``M + P - 1`` ticks with the head applied out-of-line; kept as
the eval/forward path and as the ``--pp-schedule gpipe`` fallback whose
autodiff yields the reverse pipeline (memory O(M)).

The jitted results compute exactly the same function as the plain trunk
(tests/test_pipeline.py pins loss/trajectory equivalence on the CPU mesh).

**Analytic bubble / efficiency model (SPMD lockstep).** Let F and B be one
stage's forward and backward tick cost (B ~ 2F). Every device executes the
same compiled tick body, so a tick costs F+B wall whether or not this
stage has work that tick (idle slots are zero-masked compute, not idle
time — the price of single-program pipelining on an SPMD compiler):

- 1F1B runs ``M + 2P - 1`` combined ticks -> wall = (M+2P-1)(F+B);
  bubble fraction = (2P-1)/(M+2P-1)  [M=8, P=2: 27%; M=16: 16%; M=32: 9%]
- GPipe runs an (M+P-1)-tick forward scan at F plus its autodiff reverse
  at B -> wall = (M+P-1)(F+B); bubble = (P-1)/(M+P-1)
  [M=8, P=2: 11%; M=16: 6%]

So in this SPMD formulation 1F1B pays P extra bubble ticks of wall
relative to GPipe — analytically (M+2P-1)/(M+P-1) = 1.22x at M=8/P=2,
1.12x at M=16/P=2. Measured on the 8-virtual-device CPU mesh
(scripts/pp_bench.py, dim-256 4-layer model): **1.26x and 1.15x** — the
analytic model tracks within 3-4%, the excess being the in-loop head+CE
and stash-ring bookkeeping. (The asynchronous-dispatch 1F1B of GPU
frameworks has no such penalty because stages genuinely idle rather than
execute masked ticks.) Its win is MEMORY: 0.145x GPipe's activation
allocation at M=8/P=2 (test_pipeline_1f1b_activation_memory), plus
per-microbatch per-vocab-shard logits — 1F1B is the default because
activation memory, not wall, is what kills long-context/deep-model PP
configs, and the wall gap closes as 1/M. Use ``--pp-schedule gpipe``
when M is small and memory is not binding.
"""

import jax
import jax.numpy as jnp
from ..utils.jax_compat import shard_map
from jax.sharding import PartitionSpec as P

from ..parallel.mesh import active_mesh


def pipeline_hidden(model, params, x, positions, mesh=None,
                    microbatches: int = 0) -> jax.Array:
    """Run the scan-form trunk through the GPipe schedule.

    ``x``: (B, S, D) embedded activations (global view); returns the final
    hidden states (B, S, D). Caller applies embed before and head after.
    """
    from ..models.llama import TransformerBlock

    mesh = mesh or active_mesh()
    pp = mesh.shape["pipe"]
    n_micro = microbatches or pp
    cfg = model.cfg
    if cfg.n_layers % pp:
        raise ValueError(f"n_layers {cfg.n_layers} not divisible by pp {pp}")
    if x.shape[0] % n_micro:
        raise ValueError(
            f"batch {x.shape[0]} not divisible by microbatches {n_micro}")

    from flax import linen as nn

    block_cls = TransformerBlock
    if cfg.remat:
        block_cls = nn.remat(TransformerBlock, prevent_cse=False,
                             static_argnums=())
    block = block_cls(cfg)
    stacked = params["layers"]["block"]

    def local_layers(stack_local, h, pos):
        return _stage_layers(block, cfg, stack_local, h, pos,
                             collect_aux=False)[0]

    compute_dtype = x.dtype
    b, seq, d = x.shape
    mb = b // n_micro

    # Split into microbatches OUTSIDE the manual region, pad with the pp-1
    # drain ticks, and pin the sharding explicitly: the scan below then
    # consumes its xs natively (no dynamic_index over an axis the reshape
    # silently left batch-sharded — that indexing forced the partitioner
    # into an involuntary full rematerialization per tick). The constraint
    # puts the batch sharding on the per-microbatch batch dim when it
    # divides, and degrades to explicit (voluntary) replication when it
    # does not (tiny dryrun shapes).
    from ..parallel.sharding import constrain, suspend_constraints
    micro = x.astype(jnp.float32).reshape(n_micro, mb, seq, d)
    micro = jnp.concatenate(
        [micro, jnp.zeros((pp - 1, mb, seq, d), jnp.float32)], axis=0)
    micro = constrain(micro, None, "batch", None, None)

    def body(stack_local, micro, pos):
        s = jax.lax.axis_index("pipe")
        # boundary values travel in fp32: the cotangent of a replicated
        # (P()) shard_map input is accumulated with a psum over 'pipe', and
        # bf16 psums inside a partial-manual shard_map trip an XLA
        # partitioner CHECK (jax 0.9 / XLA CPU) — compute stays bf16
        micro = micro.astype(compute_dtype)
        ring = [(i, (i + 1) % pp) for i in range(pp)]

        # One lax.scan over the ticks (not an unrolled Python loop): the
        # layer scan inside is traced once, keeping compile time O(1) in
        # microbatches — the same reason the trunk itself is scanned.
        # Stage 0 injects microbatch t at tick t; stage P-1 emits finished
        # microbatch t-P+1, so the stacked ys hold them from tick P-1 on.
        def tick(recv, x_t):
            xin = jnp.where(s == 0, x_t, recv)
            out = local_layers(stack_local, xin, pos)
            recv = jax.lax.ppermute(out, "pipe", ring)
            return recv, out

        recv = jnp.zeros((mb, seq, d), compute_dtype)
        _, outs = jax.lax.scan(tick, recv, micro)
        outs = outs[pp - 1:]  # (n_micro, mb, seq, d), static slice
        outs = jnp.where(s == pp - 1, outs, jnp.zeros((), compute_dtype))
        # broadcast the last stage's result to every stage; fp32 for the
        # same partitioner reason as above, and it doubles as the fp32
        # boundary on the way out
        outs = jax.lax.psum(outs.astype(jnp.float32), "pipe")
        return outs.reshape(b, seq, d)

    stack_specs = jax.tree_util.tree_map(
        lambda leaf: P("pipe"), stacked)
    fn = shard_map(body, mesh=mesh,
                   in_specs=(stack_specs, P(), P()),
                   out_specs=P(), axis_names={"pipe"}, check_vma=False)
    with suspend_constraints():
        # constraints inside the manual region would stamp all-auto-mesh
        # shardings that break the shard_map transpose (see sharding.py)
        hidden = fn(stacked, micro, positions)
    return hidden.astype(x.dtype)


def pipeline_apply(model, params, tokens, mesh=None,
                   microbatches: int = 0) -> jax.Array:
    """Full forward (embed -> pipelined trunk -> head) -> logits."""
    x = model.apply({"params": params}, tokens, method="embed")
    positions = model.default_positions(tokens.shape[1])
    hidden = pipeline_hidden(model, params, x, positions, mesh=mesh,
                             microbatches=microbatches)
    return model.apply({"params": params}, hidden, method="head")


def _stage_layers(block, cfg, stack_local, h, pos, collect_aux):
    """Apply one stage's slice of the layer stack to ``h``.

    Shared by the GPipe forward (pipeline_hidden) and the 1F1B tick loop
    (pipeline_value_and_grad) so their per-layer application can never
    diverge. Control flow follows ``cfg.pp_stage_unroll`` (default on):
    a static Python unroll over ``tree[i]`` slices — measured 22.5%
    faster than the lax.scan form on the chip and 20% through the full
    1F1B step on the CPU mesh (configs.py) — or the lax.scan form
    (O(1) compile in stage depth). ``collect_aux`` accumulates the MoE
    routers' sown aux. Returns (h_out, summed aux — 0.0 when not
    collecting)."""
    if cfg.pp_stage_unroll:
        aux = jnp.zeros((), jnp.float32)
        n_local = jax.tree_util.tree_leaves(stack_local)[0].shape[0]
        for i in range(n_local):
            layer_params = jax.tree_util.tree_map(lambda a: a[i],
                                                  stack_local)
            if collect_aux:
                h, mut = block.apply({"params": layer_params}, h, pos,
                                     mutable=["losses"])
                aux = aux + sum(jnp.sum(leaf) for leaf in
                                jax.tree_util.tree_leaves(mut))
            else:
                h = block.apply({"params": layer_params}, h, pos)
        return h, aux
    if collect_aux:
        def step(carry, layer_params):
            h, aux = carry
            out, mut = block.apply({"params": layer_params}, h, pos,
                                   mutable=["losses"])
            aux = aux + sum(jnp.sum(leaf) for leaf in
                            jax.tree_util.tree_leaves(mut))
            return (out, aux), None

        (h, aux), _ = jax.lax.scan(
            step, (h, jnp.zeros((), jnp.float32)), stack_local)
        return h, aux

    def step(c, layer_params):
        return block.apply({"params": layer_params}, c, pos), None

    out, _ = jax.lax.scan(step, h, stack_local)
    return out, jnp.zeros((), jnp.float32)


def _rmsnorm(scale, h, eps):
    """Functional twin of models/llama.py RMSNorm (fp32 internal, cast
    back, then scale) for the in-loop tail's explicit VJP."""
    hf = h.astype(jnp.float32)
    normed = hf * jax.lax.rsqrt(
        jnp.mean(hf * hf, axis=-1, keepdims=True) + eps)
    return normed.astype(h.dtype) * scale.astype(h.dtype)


def pipeline_value_and_grad(model, params, tokens, labels, mesh=None,
                            microbatches: int = 0):
    """1F1B train step core: ``((loss, num_valid), grads)``.

    Drop-in for ``jax.value_and_grad(loss_fn, has_aux=True)`` when the
    trunk is pipelined (training/step.py dispatches here). The tick loop
    is never differentiated; see the module docstring for the schedule.

    Lockstep timetable (stage ``s``, microbatch ``m``, ``P`` stages,
    ``M`` microbatches, one combined fwd+bwd slot per tick ``t``):

    - forward of ``m`` at stage ``s``:  ``t = s + m``  (GPipe issue rate)
    - head+CE (all stages, vocab-sharded) for ``m``: ``t = m + P - 1``
    - backward of ``m`` at stage ``s``: ``t = m + 2P - 1 - s``

    so ``T = M + 2P - 1`` ticks total and a stage holds at most ``2P-1``
    stashed microbatch inputs — O(P) trunk residuals, independent of M
    (the embed boundary/cotangent buffers stay O(B)). Loss semantics
    match grad accumulation (training/step.py): per-token 1/N cotangents
    with N the global valid count, and per-microbatch MoE aux weighted by
    the microbatch's valid tokens.
    """
    from flax import linen as nn

    from ..models.llama import TransformerBlock
    from ..ops.cross_entropy import DEFAULT_BLOCK
    from ..ops.fused_ce import _bwd_accum, _raw_stats
    from ..parallel.sharding import (
        constrain,
        suspend_constraints,
        vocab_shard_axes,
    )
    from ..training.step import IGNORE_INDEX

    mesh = mesh or active_mesh()
    pp = mesh.shape["pipe"]
    cfg = model.cfg
    n_micro = microbatches or pp
    if cfg.n_layers % pp:
        raise ValueError(f"n_layers {cfg.n_layers} not divisible by pp {pp}")
    if tokens.shape[0] % n_micro:
        raise ValueError(
            f"batch {tokens.shape[0]} not divisible by microbatches "
            f"{n_micro}")
    expected = {"tok_embeddings", "layers", "norm", "output"}
    if set(params) != expected:
        raise ValueError(
            f"pipelined grads cover params {sorted(expected)}; tree has "
            f"{sorted(params)}")

    b, seq = tokens.shape

    # ---- embed, out-of-line under the auto partitioner; its VJP turns the
    # pipeline's x-cotangent into the (vocab-sharded) table gradient
    emb_params = {"tok_embeddings": params["tok_embeddings"]}

    def embed_fn(ep):
        merged = dict(params, **ep)
        return model.apply({"params": merged}, tokens, method="embed")

    x, embed_vjp = jax.vjp(embed_fn, emb_params)
    positions = model.default_positions(seq)
    compute_dtype = x.dtype
    d = x.shape[-1]
    mb = b // n_micro

    valid = labels != IGNORE_INDEX
    num_valid = jnp.sum(valid)
    nf = jnp.maximum(num_valid.astype(jnp.float32), 1.0)
    safe_labels = jnp.where(valid, labels, 0)

    micro = x.reshape(n_micro, mb, seq, d)
    labels_m = safe_labels.reshape(n_micro, mb, seq)
    vmask_m = valid.reshape(n_micro, mb, seq)
    n_per_micro = jnp.sum(vmask_m, axis=(1, 2)).astype(jnp.float32)  # (M,)

    n_ticks = n_micro + 2 * pp - 1
    n_slots = 2 * pp - 1  # stash ring capacity = max in-flight microbatches

    # xs, padded to the tick count and pinned batch-sharded on the mb dim
    # (same reasoning as the GPipe path above): microbatch m enters stage 0
    # at tick m; labels/vmask align with the head tick m + P - 1, vmask's
    # False padding doubles as the "no head work this tick" gate.
    micro_xs = jnp.concatenate(
        [micro, jnp.zeros((n_ticks - n_micro, mb, seq, d), micro.dtype)], 0)
    micro_xs = constrain(micro_xs, None, "batch", None, None)
    labels_xs = jnp.concatenate(
        [jnp.zeros((pp - 1, mb, seq), labels_m.dtype), labels_m,
         jnp.zeros((pp, mb, seq), labels_m.dtype)], 0)
    vmask_xs = jnp.concatenate(
        [jnp.zeros((pp - 1, mb, seq), bool), vmask_m,
         jnp.zeros((pp, mb, seq), bool)], 0)
    ticks = jnp.arange(n_ticks, dtype=jnp.int32)

    # ---- head weight view: (D, V) -> (D, pipe_shards, Vl). 'pipe' is the
    # MAJOR vocab axis (parallel/sharding.py) so this reshape is
    # reshard-free and stage s's slice is the contiguous [s*Vl, (s+1)*Vl);
    # any 'tensor' sub-sharding stays auto inside the slice.
    # Cast the head weight to the COMPUTE dtype, mirroring nn.Dense
    # (dtype=cfg.dtype) and the fused-CE path (training/step.py casts
    # head_w the same way): under mixed precision (fp32 master params,
    # bf16 compute) the in-loop head must round w exactly where the
    # single-device path does, or the pipelined trajectory diverges from
    # the path it claims to reproduce (ADVICE r3). dw is assembled in
    # fp32 and cast to the param dtype on return, same as autodiff of
    # the cast would produce.
    w = params["output"]["kernel"].astype(cfg.dtype)
    v = w.shape[1]
    vaxes = vocab_shard_axes(w.shape, mesh)
    # When the vocab dim is indivisible by pp (degenerate configs only —
    # every real preset's vocab divides the pipe sizes in use), the weight
    # arrives pipe-replicated and every stage runs the full-vocab tail
    # redundantly (P× head FLOPs). Accepted: gating the tail per stage
    # would need divergent lax.conds around auto-axis collectives.
    pipe_shards = pp if "pipe" in vaxes else 1
    tensor_on_vocab = "tensor" in vaxes
    vl = v // pipe_shards
    w3 = w.reshape(d, pipe_shards, vl)
    w_spec = P(None, "pipe" if pipe_shards > 1 else None, None)
    # Blocked local head when the slice is big and unsharded; dense when
    # 'tensor' co-shards it (blocked dynamic slicing over a sharded vocab
    # would make the partitioner gather — same rule as cross_entropy_loss)
    # or when it is small anyway.
    blocked = (not tensor_on_vocab) and vl > DEFAULT_BLOCK
    scale = params["norm"]["scale"]
    stacked = params["layers"]["block"]
    stack_specs = jax.tree_util.tree_map(lambda leaf: P("pipe"), stacked)
    aux_weight = float(cfg.moe_aux_weight) if cfg.moe_experts else 0.0

    block_cls = TransformerBlock
    if cfg.remat:
        block_cls = nn.remat(TransformerBlock, prevent_cse=False,
                             static_argnums=())
    block = block_cls(cfg)

    def stage_fn(stack_local, h, pos):
        """This stage's layers; returns (h_out, summed router aux)."""
        return _stage_layers(block, cfg, stack_local, h, pos,
                             collect_aux=bool(cfg.moe_experts))

    def local_head_stats(h_norm, labels_loc, w_local):
        if blocked:
            return _raw_stats(h_norm, w_local, labels_loc, DEFAULT_BLOCK)
        lf = jnp.dot(h_norm, w_local, preferred_element_type=jnp.float32)
        m = jnp.max(lf, axis=-1)
        l = jnp.sum(jnp.exp(lf - m[..., None]), axis=-1)
        hit = (jax.lax.broadcasted_iota(jnp.int32, lf.shape, lf.ndim - 1)
               == labels_loc[..., None])
        picked = jnp.sum(jnp.where(hit, lf, 0.0), axis=-1)
        return m, l, picked

    def local_head_bwd(h_norm, labels_loc, w_local, lse, gtok):
        if blocked:
            return _bwd_accum(h_norm, w_local, labels_loc, lse, gtok,
                              DEFAULT_BLOCK, dw_dtype=jnp.float32)
        lf = jnp.dot(h_norm, w_local, preferred_element_type=jnp.float32)
        p = jnp.exp(lf - lse[..., None])
        onehot = (jax.lax.broadcasted_iota(jnp.int32, lf.shape, lf.ndim - 1)
                  == labels_loc[..., None])
        ds = (gtok[..., None] * (p - onehot.astype(jnp.float32))
              ).astype(h_norm.dtype)
        dh = jnp.einsum("bsv,dv->bsd", ds, w_local,
                        preferred_element_type=jnp.float32)
        dw = jnp.einsum("bsd,bsv->dv", h_norm, ds,
                        preferred_element_type=jnp.float32)
        return dh, dw

    def body(stack_local, w3_local, scale_p, pos, micro_xs, labels_xs,
             vmask_xs, ticks, n_arr):
        s = jax.lax.axis_index("pipe")
        w_local = w3_local.reshape(d, vl)
        v0 = jnp.where(pipe_shards > 1, s * vl, 0)
        fwd_ring = [(i, (i + 1) % pp) for i in range(pp)]
        bwd_ring = [(i, (i - 1) % pp) for i in range(pp)]

        def tick(carry, xs_t):
            (fwd_recv, bwd_recv, hbar, stash, xbar, dstack, dw, dscale,
             nll_acc, aux_acc) = carry
            x_t, lab_t, vm_t, t = xs_t

            # ---- backward of microbatch m_b (reads the stash slot that
            # this tick's forward may immediately reuse — order matters)
            m_b = t - (2 * pp - 1) + s
            b_on = (m_b >= 0) & (m_b < n_micro)
            slot_b = jnp.where(b_on, m_b % n_slots, 0)
            x_saved = jax.lax.dynamic_index_in_dim(stash, slot_b, 0,
                                                   keepdims=False)
            g_in = jnp.where(s == pp - 1, hbar, bwd_recv)
            g_in = jnp.where(b_on, g_in, jnp.zeros_like(g_in))
            n_b = jax.lax.dynamic_index_in_dim(
                n_arr, jnp.clip(m_b, 0, n_micro - 1), 0, keepdims=False)
            # VJPs are linear in the cotangent: zeroed (g_in, aux_ct) on
            # off-schedule ticks yield exactly-zero grad contributions, so
            # no masking of the accumulators is needed.
            aux_ct = jnp.where(b_on, aux_weight * n_b / nf, 0.0)
            _, vjp_fn = jax.vjp(
                lambda sl, h: stage_fn(sl, h, pos), stack_local, x_saved)
            dstack_i, dx = vjp_fn((g_in, aux_ct))
            dstack = jax.tree_util.tree_map(
                lambda a, gi: a + gi.astype(jnp.float32), dstack, dstack_i)
            # stage 0's dx is the embed cotangent; park it in the (M+1)-row
            # buffer (row M is the spill row for every masked write, so the
            # update runs unconditionally — no full-buffer select per tick)
            wr = jnp.where((s == 0) & b_on,
                           jnp.clip(m_b, 0, n_micro - 1), n_micro)
            xbar = jax.lax.dynamic_update_index_in_dim(xbar, dx, wr, 0)

            # ---- forward of microbatch m_f
            m_f = t - s
            f_on = (m_f >= 0) & (m_f < n_micro)
            xin = jnp.where(s == 0, x_t, fwd_recv)
            out_f, aux_f = stage_fn(stack_local, xin, pos)
            n_f = jax.lax.dynamic_index_in_dim(
                n_arr, jnp.clip(m_f, 0, n_micro - 1), 0, keepdims=False)
            aux_acc = aux_acc + jnp.where(f_on, aux_f * n_f, 0.0)
            wrf = jnp.where(f_on, m_f % n_slots, n_slots)  # spill row
            stash = jax.lax.dynamic_update_index_in_dim(stash, xin, wrf, 0)

            # ---- head+CE for m_t = t - (P-1), whose forward just left the
            # last stage. All stages participate on their vocab slice; the
            # all-False vmask padding makes off-schedule ticks contribute
            # exact zeros (gtok = 0) with no NaN hazard (stats stay finite
            # on any input). psums are fp32 (bf16 psum trips XLA).
            h_m = jax.lax.psum(
                jnp.where(s == pp - 1, out_f, 0).astype(jnp.float32),
                "pipe").astype(compute_dtype)
            h_norm, norm_vjp = jax.vjp(
                lambda sc, h: _rmsnorm(sc, h, cfg.norm_eps), scale_p, h_m)
            labels_loc = lab_t - v0
            m_l, l_l, picked_l = local_head_stats(h_norm, labels_loc,
                                                  w_local)
            if pipe_shards > 1:
                m_g = jax.lax.pmax(m_l, "pipe")
                l_g = jax.lax.psum(l_l * jnp.exp(m_l - m_g), "pipe")
                picked_g = jax.lax.psum(picked_l, "pipe")
            else:
                m_g, l_g, picked_g = m_l, l_l, picked_l
            lse = m_g + jnp.log(l_g)
            nll_acc = nll_acc + jnp.sum(
                jnp.where(vm_t, lse - picked_g, 0.0))
            gtok = jnp.where(vm_t, 1.0, 0.0) / nf
            dh_norm, dw_i = local_head_bwd(h_norm, labels_loc, w_local,
                                           lse, gtok)
            dw = dw + dw_i
            if pipe_shards > 1:
                dh_norm = jax.lax.psum(dh_norm, "pipe")
            dscale_i, dh_m = norm_vjp(dh_norm.astype(h_norm.dtype))
            dscale = dscale + dscale_i.astype(jnp.float32)

            fwd_recv = jax.lax.ppermute(out_f, "pipe", fwd_ring)
            bwd_recv = jax.lax.ppermute(dx, "pipe", bwd_ring)
            return (fwd_recv, bwd_recv, dh_m, stash, xbar, dstack, dw,
                    dscale, nll_acc, aux_acc), None

        zeros_act = jnp.zeros((mb, seq, d), compute_dtype)
        init = (
            zeros_act, zeros_act, zeros_act,
            jnp.zeros((n_slots + 1, mb, seq, d), compute_dtype),
            jnp.zeros((n_micro + 1, mb, seq, d), compute_dtype),
            jax.tree_util.tree_map(
                lambda p: jnp.zeros(p.shape, jnp.float32), stack_local),
            jnp.zeros((d, vl), jnp.float32),
            jnp.zeros((d,), jnp.float32),
            jnp.zeros((), jnp.float32),
            jnp.zeros((), jnp.float32),
        )
        carry, _ = jax.lax.scan(
            tick, init, (micro_xs, labels_xs, vmask_xs, ticks))
        (_, _, _, _, xbar, dstack, dw, dscale, nll_acc, aux_acc) = carry
        # only stage 0 wrote real rows into xbar; fp32 psum broadcasts them
        # (the one place the boundary leaves bf16 — same rule as GPipe's
        # final broadcast above). nll/dscale are already stage-uniform.
        xbar_sum = jax.lax.psum(xbar[:n_micro].astype(jnp.float32), "pipe")
        aux_total = jax.lax.psum(aux_acc, "pipe")
        return (xbar_sum, dstack, dw[:, None, :], dscale, nll_acc,
                aux_total)

    fn = shard_map(
        body, mesh=mesh,
        in_specs=(stack_specs, w_spec, P(), P(), P(), P(), P(), P(), P()),
        out_specs=(P(), stack_specs, w_spec, P(), P(), P()),
        axis_names={"pipe"}, check_vma=False)
    with suspend_constraints():
        xbar, dstack, dw3, dscale, sum_nll, aux_total = fn(
            stacked, w3, scale, positions, micro_xs, labels_xs, vmask_xs,
            ticks, n_per_micro)

    loss = (sum_nll + aux_weight * aux_total) / nf
    (demb,) = embed_vjp(xbar.astype(compute_dtype).reshape(b, seq, d))
    grads = {
        "tok_embeddings": demb["tok_embeddings"],
        "layers": {"block": jax.tree_util.tree_map(
            lambda g, p: g.astype(p.dtype), dstack, stacked)},
        "norm": {"scale": dscale.astype(scale.dtype)},
        # .astype targets the PARAM dtype (w above is the compute-dtype
        # cast view, which may differ under --master-weights fp32)
        "output": {"kernel": dw3.reshape(d, v).astype(
            params["output"]["kernel"].dtype)},
    }
    return (loss, num_valid), grads
