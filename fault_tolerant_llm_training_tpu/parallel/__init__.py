from .mesh import MESH_AXES, make_mesh, active_mesh, use_mesh
from .sharding import (
    LOGICAL_RULES,
    batch_pspec,
    constrain,
    param_pspecs,
    param_shardings,
)

__all__ = [
    "MESH_AXES",
    "make_mesh",
    "active_mesh",
    "use_mesh",
    "LOGICAL_RULES",
    "batch_pspec",
    "constrain",
    "param_pspecs",
    "param_shardings",
]
