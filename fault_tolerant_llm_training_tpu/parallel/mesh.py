"""Device mesh construction (reference has no parallelism — SURVEY.md §2.3;
this is the build's first-class replacement for the NCCL/DDP layer the
reference would have needed at scale).

Mesh axes, in order:

- ``data``     — pure data parallelism (gradient psum over ICI)
- ``pipe``     — pipeline parallelism (layer-stacked params sharded by
                 stage; microbatches rotate via ppermute — parallel/pipeline.py)
- ``fsdp``     — parameter/optimizer sharding; also shards the batch
- ``sequence`` — sequence/context parallelism (ring attention)
- ``expert``   — expert parallelism (MoE expert FFNs sharded by expert;
                 XLA inserts the token<->expert all-to-all — models/moe.py)
- ``tensor``   — tensor parallelism (Megatron-style sharded matmuls)

Collectives are inserted by XLA from the NamedShardings; on a real pod the
axes should be laid out so that ``tensor``/``sequence`` ride ICI and ``data``
can span DCN (the axis order here puts the fast-varying axes last, which maps
them to nearby devices in the default device order; ``pipe`` sits early
because a stage exchanges only one microbatch activation per tick).
"""

import contextlib
from typing import Optional

import jax
import numpy as np
from jax.sharding import Mesh

MESH_AXES = ("data", "pipe", "fsdp", "sequence", "expert", "tensor")

_ACTIVE_MESH: Optional[Mesh] = None


def make_mesh(dp: int = -1, fsdp: int = 1, sp: int = 1, tp: int = 1,
              pp: int = 1, ep: int = 1, devices=None) -> Mesh:
    """Build a ('data','pipe','fsdp','sequence','expert','tensor') mesh;
    dp=-1 fills the remaining devices."""
    devices = list(jax.devices()) if devices is None else list(devices)
    n = len(devices)
    denom = pp * fsdp * sp * ep * tp
    if dp == -1:
        if n % denom:
            raise ValueError(
                f"{n} devices not divisible by pp*fsdp*sp*ep*tp={denom}")
        dp = n // denom
    total = dp * denom
    if total > n:
        raise ValueError(f"mesh {dp}x{pp}x{fsdp}x{sp}x{ep}x{tp}={total} "
                         f"exceeds {n} devices")
    arr = np.asarray(devices[:total]).reshape(dp, pp, fsdp, sp, ep, tp)
    return Mesh(arr, MESH_AXES)


def active_mesh() -> Optional[Mesh]:
    return _ACTIVE_MESH


@contextlib.contextmanager
def use_mesh(mesh: Optional[Mesh]):
    """Install ``mesh`` as the process-wide active mesh.

    Model code resolves activation sharding constraints (and ring attention
    its axis) against this; ``None`` or a trivial 1-device mesh disables
    constraints so the same model code runs unsharded on CPU."""
    global _ACTIVE_MESH
    prev = _ACTIVE_MESH
    _ACTIVE_MESH = mesh
    try:
        if mesh is not None:
            with mesh:
                yield mesh
        else:
            yield None
    finally:
        _ACTIVE_MESH = prev


def mesh_axis_size(axis: str) -> int:
    mesh = active_mesh()
    if mesh is None:
        return 1
    return mesh.shape[axis]
