"""Checkpoint interop with the reference's ``torch.save`` format.

The reference checkpoints one monolithic dict
``{model, optimizer, lr_scheduler, training_step}`` (ref: utils.py:74-81)
where ``model`` is the torch ``state_dict`` of the Llama-style transformer
(ref: model.py:315-380) and ``optimizer`` is torch AdamW's
``{state: {idx: {step, exp_avg, exp_avg_sq}}, param_groups}``. This module
maps that format to/from this framework's ``TrainState`` so a reference user
can bring a half-trained torch checkpoint to TPU (and go back):

- parameter names: torch registration order / dotted names
  (``layers.3.attention.wq.weight``) <-> flax tree paths
  (``layers_3/attention/wq/kernel``);
- orientation: ``nn.Linear`` stores (out, in); flax ``Dense`` kernels are
  (in, out) — transposed both ways. Embeddings and norm scales map 1:1
  (``weight`` <-> ``embedding`` / ``scale``);
- optimizer moments: torch ``exp_avg``/``exp_avg_sq`` live in parameter
  space, so they transpose exactly like their parameters into optax's
  ``ScaleByAdamState.mu``/``nu``; torch's per-param ``step`` and the LambdaLR
  ``last_epoch`` both equal the training step, which here is the single
  update count (``training/state.py``: the schedule is a pure function of
  it);
- RoPE needs no weight transform: the reference's complex-arithmetic
  rotation and our real interleaved cos/sin form compute the identical
  function of the same weights (pinned by tests/test_torch_parity.py), and
  the reference's ``freqs_cis`` buffer is non-persistent (model.py:342-344)
  so it never appears in checkpoints.

Conversion is lossless: dtypes are preserved leaf-for-leaf, so a
convert -> convert round trip is bit-exact and a resumed run continues with
the same loss trajectory as a native resume (tests/test_convert.py).

No torch import is needed: the torch pickle is read/written through
``torch.load``/``torch.save`` only in the CLI (scripts/convert_checkpoint.py);
this module works on plain numpy-convertible leaves.
"""

from typing import Dict, List, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax


def reference_param_names(n_layers: int) -> List[Tuple[str, Tuple[str, ...], bool]]:
    """(torch_name, flax_path, transpose) in the reference's registration
    order (ref model.py: tok_embeddings at :340, per-block wq/wk/wv/wo at
    :170-177, w1/w2/w3 at :249-251, attention_norm/ffn_norm at :291-292,
    then norm :350 and output :352) — which is also torch AdamW's param
    index order, since ``model.parameters()`` follows registration."""
    names = [("tok_embeddings.weight", ("tok_embeddings", "embedding"), False)]
    for i in range(n_layers):
        for lin in ("wq", "wk", "wv", "wo"):
            names.append((f"layers.{i}.attention.{lin}.weight",
                          (f"layers_{i}", "attention", lin, "kernel"), True))
        for lin in ("w1", "w2", "w3"):
            names.append((f"layers.{i}.feed_forward.{lin}.weight",
                          (f"layers_{i}", "feed_forward", lin, "kernel"), True))
        names.append((f"layers.{i}.attention_norm.weight",
                      (f"layers_{i}", "attention_norm", "scale"), False))
        names.append((f"layers.{i}.ffn_norm.weight",
                      (f"layers_{i}", "ffn_norm", "scale"), False))
    names.append(("norm.weight", ("norm", "scale"), False))
    names.append(("output.weight", ("output", "kernel"), True))
    return names


def _get(tree, path):
    for k in path:
        tree = tree[k]
    return tree


def _set(tree, path, value):
    for k in path[:-1]:
        tree = tree.setdefault(k, {})
    tree[path[-1]] = value


def _to_torch_orientation(tree, n_layers) -> Dict[str, np.ndarray]:
    """Flax param-shaped tree -> {torch_name: array} (transposing linears)."""
    out = {}
    for torch_name, path, transpose in reference_param_names(n_layers):
        arr = np.asarray(_get(tree, path))
        out[torch_name] = arr.T if transpose else arr
    return out


def _from_torch_orientation(sd: Dict[str, np.ndarray], n_layers) -> dict:
    """{torch_name: array} -> flax param-shaped tree (transposing linears)."""
    tree: dict = {}
    for torch_name, path, transpose in reference_param_names(n_layers):
        if torch_name not in sd:
            raise KeyError(
                f"reference checkpoint is missing {torch_name!r}; is the "
                f"--model preset (n_layers={n_layers}) right for this file?")
        arr = np.asarray(sd[torch_name])
        _set(tree, path, arr.T if transpose else arr)
    return tree


def state_to_torch_ckpt(state, n_layers: int, learning_rate: float,
                        warmup_steps: int = 10,
                        weight_decay: float = 0.01,
                        lr_schedule: str = "constant",
                        decay_steps: int = 0) -> dict:
    """TrainState -> the reference's checkpoint dict (numpy leaves).

    ``optimizer``/``lr_scheduler`` entries follow torch AdamW's and
    LambdaLR's ``state_dict()`` schema (ref loads them at train.py:70-77).
    The exported ``lr``/``_last_lr`` carry the *schedule-scaled* current
    rate — what a native torch checkpoint would hold mid-warmup or
    mid-decay — via the same schedule resolution the trainer uses
    (utils/schedules.py build_schedule)."""
    from ..utils.schedules import build_schedule

    from ..models.llama import unstack_layer_params

    step = int(np.asarray(state.step))
    # same schedule resolution as the trainer (build_schedule), so a
    # cosine run exports its true mid-decay rate
    current_lr = float(build_schedule(learning_rate, warmup_steps,
                                      lr_schedule, decay_steps)(step))
    # scan-form states (layer_impl="scan": layers/block/... with a leading
    # n_layers axis) export through the loop layout the reference uses
    maybe_unstack = (lambda t: unstack_layer_params(t, n_layers)
                     if "layers" in t else t)
    first_block = (state.params.get("layers_0")
                   or state.params.get("layers", {}).get("block", {}))
    if "experts" in first_block.get("feed_forward", {}):
        raise ValueError(
            "MoE states (moe_experts > 0) have no reference-format "
            "equivalent — the reference model is dense (ref model.py:218-"
            "254); only dense checkpoints convert")
    adams = [s for s in jax.tree_util.tree_leaves(
        state.opt_state,
        is_leaf=lambda x: isinstance(x, optax.ScaleByAdamState))
        if isinstance(s, optax.ScaleByAdamState)]
    if not adams:
        raise ValueError("opt_state holds no ScaleByAdamState; only AdamW "
                         "states convert to the reference format")
    adam = adams[0]
    mu = _to_torch_orientation(maybe_unstack(adam.mu), n_layers)
    nu = _to_torch_orientation(maybe_unstack(adam.nu), n_layers)
    names = [n for n, _, _ in reference_param_names(n_layers)]
    opt_state = {
        i: {"step": np.float32(step), "exp_avg": mu[name],
            "exp_avg_sq": nu[name]}
        for i, name in enumerate(names)
    }
    return {
        "model": _to_torch_orientation(maybe_unstack(state.params), n_layers),
        "optimizer": {
            "state": opt_state,
            "param_groups": [{
                "lr": current_lr, "betas": (0.9, 0.999), "eps": 1e-8,
                "weight_decay": weight_decay, "amsgrad": False,
                "maximize": False, "foreach": None, "capturable": False,
                "differentiable": False, "fused": None,
                "params": list(range(len(names))),
            }],
        },
        # LambdaLR schema (its load_state_dict pops 'lr_lambdas' first)
        "lr_scheduler": {"last_epoch": step, "_step_count": step + 1,
                         "lr_lambdas": [None], "base_lrs": [learning_rate],
                         "_last_lr": [current_lr]},
        "training_step": step,
    }


def state_from_torch_ckpt(ckpt: dict, model, optimizer, param_dtype):
    """The reference's checkpoint dict -> TrainState.

    ``model``/``optimizer`` are this framework's Transformer and optax
    transform — the optimizer is initialized for structure, then the Adam
    moments and every update count are replaced from the checkpoint. When
    the model is scan-form (layer_impl="scan"), the imported trees are
    layer-stacked to match."""
    from ..models.llama import stack_layer_params
    from ..training.state import TrainState

    n_layers = model.cfg.n_layers
    step = int(ckpt["training_step"])
    maybe_stack = (lambda t: stack_layer_params(t, n_layers)
                   if model.cfg.layer_impl == "scan" else t)
    cast = lambda t: jax.tree_util.tree_map(  # noqa: E731
        lambda a: jnp.asarray(a, param_dtype), t)
    params = cast(maybe_stack(_from_torch_orientation(ckpt["model"],
                                                      n_layers)))

    names = [n for n, _, _ in reference_param_names(n_layers)]
    # normalize: torch state keys may round-trip as strings (e.g. JSON)
    opt = {int(k): v for k, v in ckpt["optimizer"]["state"].items()}
    if sorted(opt) != list(range(len(names))):
        raise ValueError(
            f"optimizer state has param indices {sorted(opt)} but the "
            f"{n_layers}-layer reference model has {len(names)} parameters")
    mu_sd = {name: np.asarray(opt[i]["exp_avg"])
             for i, name in enumerate(names)}
    nu_sd = {name: np.asarray(opt[i]["exp_avg_sq"])
             for i, name in enumerate(names)}
    mu = cast(maybe_stack(_from_torch_orientation(mu_sd, n_layers)))
    nu = cast(maybe_stack(_from_torch_orientation(nu_sd, n_layers)))

    opt_state = optimizer.init(params)
    count = jnp.asarray(step, jnp.int32)

    def fix(entry):
        if isinstance(entry, optax.ScaleByAdamState):
            return entry._replace(count=count, mu=mu, nu=nu)
        if isinstance(entry, optax.ScaleByScheduleState):
            return entry._replace(count=count)
        return entry

    opt_state = jax.tree_util.tree_map(
        fix, opt_state,
        is_leaf=lambda x: isinstance(
            x, (optax.ScaleByAdamState, optax.ScaleByScheduleState)))
    return TrainState(step=jnp.asarray(step, jnp.int32), params=params,
                      opt_state=opt_state)
