"""Orbax checkpoint manager (ref: utils.py:74-81 save; train.py:20-24 load).

The reference writes one monolithic ``torch.save`` dict named
``checkpoint_{JOBID}.ckpt`` (45 GB, 33.6 s, single writer — BASELINE.md) and
reconstructs the data position by replaying N batches (train.py:36-39). The
TPU-native design:

- **sharded, async** Orbax writes: every host writes its own param shards in
  parallel; training can continue while the write drains (periodic saves),
  and fault-path saves block only until commit;
- **atomic commit**: Orbax finalizes a step directory only after all shards
  land, fixing the reference's truncation race (a SIGTERM during the 33 s
  torch.save leaves a corrupt file — SURVEY.md §5.3);
- **data-iterator state saved in-band** (JSON), so resume is O(1) instead of
  O(steps) replay;
- directory layout keeps the reference's job-id naming contract:
  ``{checkpoint_path}/checkpoint_{JOBID}/{step}/...`` — the chained job passes
  the previous job's id exactly like ``sbatch train.sh $JOBID``
  (ref: train.sh:24-27, utils.py:84).
"""

import os
from typing import Any, Optional, Tuple

import orbax.checkpoint as ocp

from ..utils.sync import hard_sync


class CheckpointManager:
    def __init__(self, checkpoint_path: str, job_id: str,
                 enable_async: bool = True, max_to_keep: int = 2):
        self.directory = os.path.join(
            os.path.abspath(checkpoint_path), f"checkpoint_{job_id}")
        options = ocp.CheckpointManagerOptions(
            max_to_keep=max_to_keep,
            enable_async_checkpointing=enable_async,
            create=True,
        )
        self._mngr = ocp.CheckpointManager(self.directory, options=options)

    def save(self, step: int, state: Any, data_state: dict,
             wait: bool = False) -> int:
        """Async sharded save of the TrainState + data-iterator position.
        ``wait=True`` blocks until the atomic commit (fault path)."""
        hard_sync(state)  # value-dependent barrier; see utils/sync.py
        self._mngr.save(
            step,
            args=ocp.args.Composite(
                state=ocp.args.StandardSave(state),
                data=ocp.args.JsonSave(data_state),
            ),
        )
        if wait:
            self._mngr.wait_until_finished()
        return step

    def latest_step(self) -> Optional[int]:
        return self._mngr.latest_step()

    def restore(self, abstract_state: Any,
                step: Optional[int] = None) -> Tuple[Any, dict, int]:
        """Restore (state, data_state, step). ``abstract_state`` is a
        ShapeDtypeStruct pytree (with shardings) from ``jax.eval_shape`` —
        params land directly as sharded device arrays on the current mesh,
        the equivalent of the reference's cpu-load + load_state_dict
        (train.py:22,56-58) without the host bounce."""
        step = self.latest_step() if step is None else step
        if step is None:
            raise FileNotFoundError(f"no checkpoint steps in {self.directory}")
        restored = self._mngr.restore(
            step,
            args=ocp.args.Composite(
                state=ocp.args.StandardRestore(abstract_state),
                data=ocp.args.JsonRestore(),
            ),
        )
        return restored["state"], restored["data"], step

    def wait_until_finished(self) -> None:
        self._mngr.wait_until_finished()

    def close(self) -> None:
        self._mngr.close()
