"""Orbax checkpoint manager (ref: utils.py:74-81 save; train.py:20-24 load).

The reference writes one monolithic ``torch.save`` dict named
``checkpoint_{JOBID}.ckpt`` (45 GB, 33.6 s, single writer — BASELINE.md) and
reconstructs the data position by replaying N batches (train.py:36-39). The
TPU-native design:

- **sharded, async** Orbax writes: every host writes its own param shards in
  parallel; training can continue while the write drains (periodic saves),
  and fault-path saves block only until commit;
- **atomic commit**: Orbax finalizes a step directory only after all shards
  land, fixing the reference's truncation race (a SIGTERM during the 33 s
  torch.save leaves a corrupt file — SURVEY.md §5.3);
- **data-iterator state saved in-band** (JSON), so resume is O(1) instead of
  O(steps) replay;
- directory layout keeps the reference's job-id naming contract:
  ``{checkpoint_path}/checkpoint_{JOBID}/{step}/...`` — the chained job passes
  the previous job's id exactly like ``sbatch train.sh $JOBID``
  (ref: train.sh:24-27, utils.py:84);
- **write-path tuning for the USR1 deadline**: Orbax's default zstd
  compression saves ~8% disk on weight tensors but costs 3x wall on one
  core (2.15 GB probe state: 22.1 s compressed vs 7.7 s raw, and 6.4 s
  with zarr3's larger chunk pipeline — measured on this harness,
  BASELINE.md round 3). The save must fit the 120 s USR1 lead (ref
  train.sh:12) at flagship scale, so compression is off and zarr3 on;
  restore auto-detects the format, so pre-tuning checkpoints (zarr2 +
  compressed) remain loadable — both verified bit-exact;
- **budget math** (:func:`measure_write_throughput`,
  :func:`estimate_save_seconds`): the Trainer probes the checkpoint
  filesystem once at construction and logs whether the estimated save
  fits the signal lead, instead of discovering a blown deadline at the
  first preemption.
"""

import os
import time
from typing import Any, Optional, Tuple

import numpy as np
import orbax.checkpoint as ocp

from ..utils.sync import hard_sync

# Fraction of raw filesystem write throughput the tuned Orbax pipeline
# achieves end-to-end (serialization + chunking + commit). Measured on the
# build harness: 0.33 GB/s orbax vs 0.70 GB/s raw dd on the same disk with
# the same 2.15 GB state (BASELINE.md round 3). Deliberately conservative —
# the estimate guards a hard deadline.
ORBAX_WRITE_EFFICIENCY = 0.45


def state_bytes(tree) -> int:
    """Total bytes of a (possibly abstract) state pytree — the one
    definition shared by the budget estimate and the observed-save log
    (training/loop.py), so they can never diverge."""
    import jax

    return sum(int(np.prod(leaf.shape)) * leaf.dtype.itemsize
               for leaf in jax.tree_util.tree_leaves(tree))


def measure_write_throughput(directory: str,
                             probe_bytes: int = 128 * 2**20) -> float:
    """One-shot raw write throughput of ``directory``'s filesystem, in
    bytes/s (fsync'd, incompressible-ish payload so smart filesystems
    cannot fake it). ~0.2 s at the default size on local SSD. The probe
    file is per-process: on a pod every host probes the shared filesystem
    concurrently, and a shared name would make them contend on one file
    (and race each other's os.remove), measuring noise."""
    import jax

    os.makedirs(directory, exist_ok=True)
    path = os.path.join(directory, f".write_probe.{jax.process_index()}")
    # Genuinely random payload: a counter pattern compresses several-fold
    # on filesystems with transparent compression (ZFS lz4 etc.), which
    # would inflate the measured throughput and silently suppress the
    # budget warning for the incompressible real weights.
    payload = np.random.default_rng(0).integers(
        0, np.iinfo(np.uint64).max, probe_bytes // 8, dtype=np.uint64)
    try:
        t0 = time.monotonic()
        with open(path, "wb") as f:
            f.write(memoryview(payload))
            f.flush()
            os.fsync(f.fileno())
        dt = time.monotonic() - t0
    finally:
        try:
            os.remove(path)
        except OSError:
            pass
    return probe_bytes / max(dt, 1e-6)


def estimate_save_seconds(state_bytes_per_host: int,
                          raw_throughput: float) -> float:
    """Expected blocking-save wall time for this host's shard of the
    state, from the measured raw throughput derated by the Orbax
    pipeline's measured efficiency."""
    return state_bytes_per_host / max(raw_throughput
                                      * ORBAX_WRITE_EFFICIENCY, 1e-6)


def _pytree_handler_kwargs() -> dict:
    """zarr3 without compression (module docstring: 3x faster saves for ~8%
    more disk). ``use_compression`` only exists on newer orbax; older ones
    (0.7.x) write zarr3 uncompressed by default, so just drop the kwarg."""
    import inspect

    kwargs = {"use_zarr3": True}
    params = inspect.signature(ocp.PyTreeCheckpointHandler.__init__).parameters
    if "use_compression" in params:
        kwargs["use_compression"] = False
    return kwargs


class CheckpointManager:
    def __init__(self, checkpoint_path: str, job_id: str,
                 enable_async: bool = True, max_to_keep: int = 2):
        self.directory = os.path.join(
            os.path.abspath(checkpoint_path), f"checkpoint_{job_id}")
        options = ocp.CheckpointManagerOptions(
            max_to_keep=max_to_keep,
            enable_async_checkpointing=enable_async,
            create=True,
        )
        self._mngr = ocp.CheckpointManager(
            self.directory, options=options,
            # see module docstring: 3x faster saves for ~8% more disk;
            # the deadline is the product, the disk is not. (An explicit
            # item_handlers dict disables per-item auto-resolution, so
            # the JSON data item must be registered alongside.)
            item_handlers={
                "state": ocp.PyTreeCheckpointHandler(**_pytree_handler_kwargs()),
                "data": ocp.JsonCheckpointHandler(),
            })
        self.last_save_seconds: Optional[float] = None

    def save(self, step: int, state: Any, data_state: dict,
             wait: bool = False) -> int:
        """Async sharded save of the TrainState + data-iterator position.
        ``wait=True`` blocks until the atomic commit (fault path) and
        records the wall time in ``last_save_seconds`` — the observed
        number the budget estimate exists to predict."""
        hard_sync(state)  # value-dependent barrier; see utils/sync.py
        t0 = time.monotonic()
        self._mngr.save(
            step,
            args=ocp.args.Composite(
                state=ocp.args.PyTreeSave(state),
                data=ocp.args.JsonSave(data_state),
            ),
        )
        if wait:
            self._mngr.wait_until_finished()
            self.last_save_seconds = time.monotonic() - t0
        return step

    def latest_step(self) -> Optional[int]:
        return self._mngr.latest_step()

    def restore(self, abstract_state: Any,
                step: Optional[int] = None) -> Tuple[Any, dict, int]:
        """Restore (state, data_state, step). ``abstract_state`` is a
        ShapeDtypeStruct pytree (with shardings) from ``jax.eval_shape`` —
        params land directly as sharded device arrays on the current mesh,
        the equivalent of the reference's cpu-load + load_state_dict
        (train.py:22,56-58) without the host bounce."""
        step = self.latest_step() if step is None else step
        if step is None:
            raise FileNotFoundError(f"no checkpoint steps in {self.directory}")
        restored = self._mngr.restore(
            step,
            args=ocp.args.Composite(
                # Explicit per-leaf restore args carry the TARGET mesh's
                # shardings: bare PyTreeRestore would fall back to the
                # sharding file — i.e. the SAVING topology — which breaks
                # cross-topology resume (SURVEY §7.3 hard part 3).
                state=ocp.args.PyTreeRestore(
                    abstract_state,
                    restore_args=ocp.checkpoint_utils.construct_restore_args(
                        abstract_state)),
                data=ocp.args.JsonRestore(),
            ),
        )
        return restored["state"], restored["data"], step

    def wait_until_finished(self) -> None:
        self._mngr.wait_until_finished()

    def close(self) -> None:
        self._mngr.close()
