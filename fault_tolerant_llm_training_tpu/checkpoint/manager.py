"""Orbax checkpoint manager (ref: utils.py:74-81 save; train.py:20-24 load).

The reference writes one monolithic ``torch.save`` dict named
``checkpoint_{JOBID}.ckpt`` (45 GB, 33.6 s, single writer — BASELINE.md) and
reconstructs the data position by replaying N batches (train.py:36-39). The
TPU-native design:

- **sharded, async** Orbax writes: every host writes its own param shards in
  parallel; training can continue while the write drains (periodic saves),
  and fault-path saves block only until commit;
- **atomic commit**: Orbax finalizes a step directory only after all shards
  land, fixing the reference's truncation race (a SIGTERM during the 33 s
  torch.save leaves a corrupt file — SURVEY.md §5.3);
- **data-iterator state saved in-band** (JSON), so resume is O(1) instead of
  O(steps) replay;
- directory layout keeps the reference's job-id naming contract:
  ``{checkpoint_path}/checkpoint_{JOBID}/{step}/...`` — the chained job passes
  the previous job's id exactly like ``sbatch train.sh $JOBID``
  (ref: train.sh:24-27, utils.py:84);
- **write-path tuning for the USR1 deadline**: Orbax's default zstd
  compression saves ~8% disk on weight tensors but costs 3x wall on one
  core (2.15 GB probe state: 22.1 s compressed vs 7.7 s raw, and 6.4 s
  with zarr3's larger chunk pipeline — measured on this harness,
  BASELINE.md round 3). The save must fit the 120 s USR1 lead (ref
  train.sh:12) at flagship scale, so compression is off and zarr3 on;
  restore auto-detects the format, so pre-tuning checkpoints (zarr2 +
  compressed) remain loadable — both verified bit-exact;
- **budget math** (:func:`measure_write_throughput`,
  :func:`estimate_save_seconds`): the Trainer probes the checkpoint
  filesystem once at construction and logs whether the estimated save
  fits the signal lead, instead of discovering a blown deadline at the
  first preemption.
"""

import json
import os
import time
import zlib
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import orbax.checkpoint as ocp

from ..obs import events
from ..obs.registry import REGISTRY
from ..utils.logging import (
    AUDIT_CKPT_FALLBACK_FMT,
    AUDIT_CKPT_PARTIAL_SKIPPED_FMT,
    AUDIT_CKPT_VERIFY_FAILED_FMT,
    logger,
)
from ..utils.sync import hard_sync

# Fraction of raw filesystem write throughput the tuned Orbax pipeline
# achieves end-to-end (serialization + chunking + commit). Measured on the
# build harness: 0.33 GB/s orbax vs 0.70 GB/s raw dd on the same disk with
# the same 2.15 GB state (BASELINE.md round 3). Deliberately conservative —
# the estimate guards a hard deadline.
ORBAX_WRITE_EFFICIENCY = 0.45


def state_bytes(tree) -> int:
    """Total bytes of a (possibly abstract) state pytree — the one
    definition shared by the budget estimate and the observed-save log
    (training/loop.py), so they can never diverge."""
    import jax

    return sum(int(np.prod(leaf.shape)) * leaf.dtype.itemsize
               for leaf in jax.tree_util.tree_leaves(tree))


def measure_write_throughput(directory: str,
                             probe_bytes: int = 128 * 2**20) -> float:
    """One-shot raw write throughput of ``directory``'s filesystem, in
    bytes/s (fsync'd, incompressible-ish payload so smart filesystems
    cannot fake it). ~0.2 s at the default size on local SSD. The probe
    file is per-process: on a pod every host probes the shared filesystem
    concurrently, and a shared name would make them contend on one file
    (and race each other's os.remove), measuring noise."""
    import jax

    os.makedirs(directory, exist_ok=True)
    path = os.path.join(directory, f".write_probe.{jax.process_index()}")
    # Genuinely random payload: a counter pattern compresses several-fold
    # on filesystems with transparent compression (ZFS lz4 etc.), which
    # would inflate the measured throughput and silently suppress the
    # budget warning for the incompressible real weights.
    payload = np.random.default_rng(0).integers(
        0, np.iinfo(np.uint64).max, probe_bytes // 8, dtype=np.uint64)
    try:
        t0 = time.monotonic()
        with open(path, "wb") as f:
            f.write(memoryview(payload))
            f.flush()
            os.fsync(f.fileno())
        dt = time.monotonic() - t0
    finally:
        try:
            os.remove(path)
        except OSError:
            pass
    return probe_bytes / max(dt, 1e-6)


def estimate_save_seconds(state_bytes_per_host: int,
                          raw_throughput: float) -> float:
    """Expected blocking-save wall time for this host's shard of the
    state, from the measured raw throughput derated by the Orbax
    pipeline's measured efficiency."""
    return state_bytes_per_host / max(raw_throughput
                                      * ORBAX_WRITE_EFFICIENCY, 1e-6)


# ------------------------------------------------------ integrity manifests
# Every finalized step directory gets an ``integrity.json`` mapping each
# checkpoint file (relative path) to its size and CRC32. Orbax's zarr/ocdbt
# layout stores each array's payload in its own file set under ``state/``,
# so file-level checksums ARE per-array checksums keyed by the array's path.
# The manifest is written AFTER Orbax's atomic commit (a finalized,
# digit-named directory is complete by the rename contract), verified at
# restore, and a failure falls back — audited — to the newest earlier step
# that passes. A step without a manifest (written by an older build, or by
# a job killed before its sweep) is accepted as legacy.

MANIFEST_NAME = "integrity.json"

_M_VERIFY_FAILURES = REGISTRY.counter(
    "checkpoint_verify_failures_total",
    "Checkpoint step directories that failed integrity verification at "
    "restore")
_M_LAST_SUCCESS_AGE = REGISTRY.gauge(
    "checkpoint_last_success_age_seconds",
    "Seconds since this process last finalized a checkpoint save or "
    "completed a verified restore (staleness input for SLO alerts)")
_last_success_t: Optional[float] = None


def _mark_checkpoint_success() -> None:
    global _last_success_t
    _last_success_t = time.monotonic()
    _M_LAST_SUCCESS_AGE.set(0.0)


def update_checkpoint_age_gauge() -> None:
    """Refresh ``checkpoint_last_success_age_seconds`` — called on the
    training loop's logging cadence and per serve-loop iteration, so the
    gauge ages between checkpoint events instead of freezing at 0."""
    if _last_success_t is not None:
        _M_LAST_SUCCESS_AGE.set(time.monotonic() - _last_success_t)


class CheckpointIntegrityError(RuntimeError):
    """No checkpoint step passed integrity verification."""


def _crc32_file(path: str, chunk_bytes: int = 1 << 20) -> int:
    crc = 0
    with open(path, "rb") as fh:
        while True:
            chunk = fh.read(chunk_bytes)
            if not chunk:
                break
            crc = zlib.crc32(chunk, crc)
    return crc & 0xFFFFFFFF


def _fsync_dir(path: str) -> None:
    """fsync a directory fd so a just-renamed/just-written entry is durable
    (a kill after rename but before the metadata flush could otherwise
    resurface as a half-visible step on the next mount)."""
    fd = os.open(path, os.O_RDONLY | getattr(os, "O_DIRECTORY", 0))
    try:
        os.fsync(fd)
    except OSError:
        pass  # some filesystems refuse directory fsync; rename is still atomic
    finally:
        os.close(fd)


def _manifest_files(step_dir: str) -> Dict[str, Dict[str, int]]:
    files: Dict[str, Dict[str, int]] = {}
    for root, _dirs, names in os.walk(step_dir):
        for name in names:
            if name == MANIFEST_NAME or name == MANIFEST_NAME + ".tmp":
                continue
            path = os.path.join(root, name)
            rel = os.path.relpath(path, step_dir)
            files[rel] = {"size": os.path.getsize(path),
                          "crc32": _crc32_file(path)}
    return files


def write_manifest(step_dir: str, step: int) -> None:
    """Checksum every file of a FINALIZED step dir into integrity.json
    (atomic tmp-rename write, fsync'd file and directory)."""
    manifest = {"version": 1, "step": int(step),
                "files": _manifest_files(step_dir)}
    tmp = os.path.join(step_dir, MANIFEST_NAME + ".tmp")
    with open(tmp, "w") as fh:
        json.dump(manifest, fh)
        fh.flush()
        os.fsync(fh.fileno())
    os.replace(tmp, os.path.join(step_dir, MANIFEST_NAME))
    _fsync_dir(step_dir)


def verify_step_dir(step_dir: str) -> Tuple[bool, str]:
    """Check a step dir against its manifest. Returns ``(ok, detail)``.
    Missing manifest = legacy checkpoint, accepted. Extra files (e.g.
    later-version metadata) are ignored — only manifest-listed files are
    load-bearing for the restore."""
    manifest_path = os.path.join(step_dir, MANIFEST_NAME)
    if not os.path.isdir(step_dir):
        return False, "step directory missing"
    if not os.path.isfile(manifest_path):
        return True, "no manifest (legacy checkpoint)"
    try:
        with open(manifest_path) as fh:
            manifest = json.load(fh)
    except (OSError, json.JSONDecodeError) as e:
        return False, f"unreadable manifest ({e})"
    for rel, meta in sorted(manifest.get("files", {}).items()):
        path = os.path.join(step_dir, rel)
        if not os.path.isfile(path):
            return False, f"missing file {rel}"
        size = os.path.getsize(path)
        if size != meta["size"]:
            return False, (f"size mismatch {rel} "
                           f"({size} != {meta['size']})")
        if _crc32_file(path) != meta["crc32"]:
            return False, f"crc mismatch {rel}"
    return True, "ok"


def _pytree_handler_kwargs() -> dict:
    """zarr3 without compression (module docstring: 3x faster saves for ~8%
    more disk). ``use_compression`` only exists on newer orbax; older ones
    (0.7.x) write zarr3 uncompressed by default, so just drop the kwarg."""
    import inspect

    kwargs = {"use_zarr3": True}
    params = inspect.signature(ocp.PyTreeCheckpointHandler.__init__).parameters
    if "use_compression" in params:
        kwargs["use_compression"] = False
    return kwargs


class CheckpointManager:
    def __init__(self, checkpoint_path: str, job_id: str,
                 enable_async: bool = True, max_to_keep: int = 2):
        self.directory = os.path.join(
            os.path.abspath(checkpoint_path), f"checkpoint_{job_id}")
        options = ocp.CheckpointManagerOptions(
            max_to_keep=max_to_keep,
            enable_async_checkpointing=enable_async,
            create=True,
        )
        self._mngr = ocp.CheckpointManager(
            self.directory, options=options,
            # see module docstring: 3x faster saves for ~8% more disk;
            # the deadline is the product, the disk is not. (An explicit
            # item_handlers dict disables per-item auto-resolution, so
            # the JSON data item must be registered alongside.)
            item_handlers={
                "state": ocp.PyTreeCheckpointHandler(**_pytree_handler_kwargs()),
                "data": ocp.JsonCheckpointHandler(),
            })
        self.last_save_seconds: Optional[float] = None
        self._partial_audited: set = set()

    def _finalize_integrity(self) -> None:
        """Post-commit sweep of the job's checkpoint root: write integrity
        manifests for finalized step dirs that lack one, audit (once per
        name) any leftover non-finalized temp dir, and fsync the root so
        the just-renamed entries are durable. Orbax's commit protocol makes
        a digit-named directory complete by construction — anything else
        (``<step>.orbax-checkpoint-tmp-*`` style) is an interrupted write
        the restore scan must never pick up."""
        if not os.path.isdir(self.directory):
            return
        for name in sorted(os.listdir(self.directory)):
            path = os.path.join(self.directory, name)
            if not os.path.isdir(path):
                continue
            if name.isdigit():
                if not os.path.isfile(os.path.join(path, MANIFEST_NAME)):
                    write_manifest(path, int(name))
            elif "tmp" in name and name not in self._partial_audited:
                self._partial_audited.add(name)
                events.emit_audit(
                    logger, AUDIT_CKPT_PARTIAL_SKIPPED_FMT.format(name=name),
                    "ckpt_partial_skipped", name=name)
        _fsync_dir(self.directory)

    def save(self, step: int, state: Any, data_state: dict,
             wait: bool = False) -> int:
        """Async sharded save of the TrainState + data-iterator position.
        ``wait=True`` blocks until the atomic commit (fault path) and
        records the wall time in ``last_save_seconds`` — the observed
        number the budget estimate exists to predict."""
        hard_sync(state)  # value-dependent barrier; see utils/sync.py
        if not wait:
            # The train step donates its state buffers (loop.py
            # donate_argnums): once the loop dispatches the next step, the
            # arrays this save captured are backed by buffers XLA is free
            # to reuse. Orbax's async device-to-host copy can then read
            # LATER steps' values — a torn checkpoint whose step dir name,
            # data position, and per-array contents disagree (observed:
            # dir 10 containing step-12 params beside step-10 loader
            # state; found by scripts/chaos_campaign.py). Snapshot into
            # fresh buffers (same sharding) so the async write has sole
            # ownership. Fault-path saves block, so they skip the copy.
            state = jax.tree_util.tree_map(
                lambda x: jnp.copy(x) if isinstance(x, jax.Array) else x,
                state)
        t0 = time.monotonic()
        self._mngr.save(
            step,
            args=ocp.args.Composite(
                state=ocp.args.PyTreeSave(state),
                data=ocp.args.JsonSave(data_state),
            ),
        )
        if wait:
            self._mngr.wait_until_finished()
            self.last_save_seconds = time.monotonic() - t0
            # The atomic-rename contract: a blocking save that returned
            # must have produced the finalized digit-named directory. If
            # Orbax's commit protocol ever regresses (or a filesystem
            # lies), fail HERE, not at the eventual restore.
            step_dir = os.path.join(self.directory, str(step))
            assert os.path.isdir(step_dir), (
                f"checkpoint step {step} reported saved but {step_dir} "
                f"does not exist — atomic rename contract violated")
            self._finalize_integrity()
            _mark_checkpoint_success()
        return step

    def latest_step(self) -> Optional[int]:
        return self._mngr.latest_step()

    def _verified_step(self, step: Optional[int]) -> int:
        """Integrity gate for restore: scan candidate steps newest-first,
        return the newest one whose directory passes its manifest. Every
        rejected candidate is audited (``[CKPT VERIFY] ... failed``) and
        counted; taking anything but the newest candidate is itself
        audited (``[CKPT VERIFY] Falling back ...``) so the automatic
        recovery is visible in the .out file and the flight recorder, not
        silent. Raises :class:`CheckpointIntegrityError` if nothing
        passes."""
        steps = sorted(self._mngr.all_steps(), reverse=True)
        if not steps:
            raise FileNotFoundError(f"no checkpoint steps in {self.directory}")
        if step is None:
            candidates = steps
        else:
            # An explicitly requested step still gets verified, and still
            # falls back to older steps if corrupt — recovery beats
            # precision when the alternative is a crash loop.
            candidates = [s for s in steps if s <= step] or steps
        chosen = None
        for cand in candidates:
            ok, detail = verify_step_dir(
                os.path.join(self.directory, str(cand)))
            if ok:
                chosen = cand
                break
            _M_VERIFY_FAILURES.inc()
            events.emit_audit(
                logger,
                AUDIT_CKPT_VERIFY_FAILED_FMT.format(step=cand, detail=detail),
                "ckpt_verify_failed", step=int(cand), detail=detail,
                ok=False)
        if chosen is None:
            raise CheckpointIntegrityError(
                f"no checkpoint step in {self.directory} passed integrity "
                f"verification (tried {candidates})")
        if chosen != candidates[0]:
            events.emit_audit(
                logger, AUDIT_CKPT_FALLBACK_FMT.format(step=chosen),
                "ckpt_fallback", step=int(chosen),
                rejected=[int(s) for s in candidates
                          if s > chosen])
        return chosen

    def restore(self, abstract_state: Any,
                step: Optional[int] = None) -> Tuple[Any, dict, int]:
        """Restore (state, data_state, step) — the newest step that passes
        integrity verification (see :meth:`_verified_step`; a corrupt
        newest checkpoint falls back, audited, to the previous passing
        one). ``abstract_state`` is a ShapeDtypeStruct pytree (with
        shardings) from ``jax.eval_shape`` — params land directly as
        sharded device arrays on the current mesh, the equivalent of the
        reference's cpu-load + load_state_dict (train.py:22,56-58) without
        the host bounce."""
        step = self._verified_step(step)
        restored = self._mngr.restore(
            step,
            args=ocp.args.Composite(
                # Explicit per-leaf restore args carry the TARGET mesh's
                # shardings: bare PyTreeRestore would fall back to the
                # sharding file — i.e. the SAVING topology — which breaks
                # cross-topology resume (SURVEY §7.3 hard part 3).
                state=ocp.args.PyTreeRestore(
                    abstract_state,
                    restore_args=ocp.checkpoint_utils.construct_restore_args(
                        abstract_state)),
                data=ocp.args.JsonRestore(),
            ),
        )
        _mark_checkpoint_success()
        return restored["state"], restored["data"], step

    def wait_until_finished(self) -> None:
        self._mngr.wait_until_finished()
        self._finalize_integrity()
        _mark_checkpoint_success()

    def close(self) -> None:
        self._mngr.wait_until_finished()
        self._finalize_integrity()
        self._mngr.close()
