"""JAX persistent compilation cache wiring (shared by train and serve).

Every preempt -> resubmit restart cold-compiles its AOT programs — the
train step for the trainer, a decode program plus one prefill program per
bucket (plus the speculative draft/verify pair) for the serving engine.
Cold compiles are pure MTTR: nothing useful runs while XLA rebuilds code
it already built last incarnation. Pointing ``jax_compilation_cache_dir``
at a persistent path turns that wall into a disk read.

Lives in utils/ so the training loop does not import inference/ for it;
inference/engine.py re-exports the names for backward compatibility
(serve.py, scripts/decode_bench.py, tests).
"""

import os

import jax

DEFAULT_COMPILE_CACHE_DIR = os.path.join(
    os.path.expanduser("~"), ".cache", "fault_tolerant_llm_training_tpu",
    "xla-cache")


def enable_compilation_cache(cache_dir: str = DEFAULT_COMPILE_CACHE_DIR
                             ) -> bool:
    """Point JAX's persistent compilation cache at ``cache_dir``.

    Engine builds AOT-compile a decode program plus one prefill program per
    bucket; cold that dominates small-run wall time (16.8 s of the tiny CPU
    bench), warm it is a disk read. No-ops (returns False) when ``cache_dir``
    is empty, when the user already configured a cache (the
    ``JAX_COMPILATION_CACHE_DIR`` env var / prior config.update wins), or on
    jax versions without the option. Min-compile-time/entry-size floors drop
    to 0 so even the tiny test programs cache.
    """
    if not cache_dir:
        return False
    try:
        if getattr(jax.config, "jax_compilation_cache_dir", None):
            return True  # already configured (env var or earlier call)
        jax.config.update("jax_compilation_cache_dir", cache_dir)
    except Exception:  # pragma: no cover - ancient jax
        return False
    for knob, val in (("jax_persistent_cache_min_compile_time_secs", 0.0),
                      ("jax_persistent_cache_min_entry_size_bytes", 0)):
        try:
            jax.config.update(knob, val)
        except Exception:  # pragma: no cover - knob absent on this jax
            pass
    try:
        # jax latches cache state ("disabled") at the FIRST compile of the
        # process; by the time a caller reaches here the trainer/engine has
        # usually already jitted something (mesh setup, model init), so the
        # new dir would silently never be read or written. reset_cache()
        # returns the latch to pristine and the next compile re-initializes
        # against the dir configured above.
        from jax.experimental.compilation_cache import (
            compilation_cache as _cc)
        _cc.reset_cache()
    except Exception:  # pragma: no cover - API drift across jax versions
        pass
    return True
