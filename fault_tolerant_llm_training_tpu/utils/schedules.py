"""LR schedules (ref: utils.py:32-56).

The reference schedule is linear warmup followed by a constant multiplier of
1.0 (despite its docstring claiming linear decay — ref: utils.py:37 vs 49-51).
Under ``LambdaLR`` the factor at optimizer-update ``t`` (0-indexed) is
``(t + 1) / (warmup_steps + 1)`` during warmup, then ``1.0``. We reproduce
that exactly as an optax schedule: optax passes the 0-indexed update count.
"""

import jax.numpy as jnp


def linear_warmup_constant(learning_rate: float, warmup_steps: int):
    """Return an optax schedule matching the reference's LambdaLR semantics.

    ref: utils.py:43-53 — ``(current_step + 1) / (warmup_steps + 1)`` during
    warmup (0-indexed with the +1 adjustment), then a constant 1.0 factor.
    """

    def schedule(count):
        factor = jnp.minimum((count + 1.0) / (warmup_steps + 1.0), 1.0)
        return learning_rate * factor

    return schedule
