"""LR schedules (ref: utils.py:32-56).

The reference schedule is linear warmup followed by a constant multiplier of
1.0 (despite its docstring claiming linear decay — ref: utils.py:37 vs 49-51).
Under ``LambdaLR`` the factor at optimizer-update ``t`` (0-indexed) is
``(t + 1) / (warmup_steps + 1)`` during warmup, then ``1.0``. We reproduce
that exactly as an optax schedule: optax passes the 0-indexed update count.
"""

import jax.numpy as jnp


def linear_warmup_constant(learning_rate: float, warmup_steps: int):
    """Return an optax schedule matching the reference's LambdaLR semantics.

    ref: utils.py:43-53 — ``(current_step + 1) / (warmup_steps + 1)`` during
    warmup (0-indexed with the +1 adjustment), then a constant 1.0 factor.
    """

    def schedule(count):
        factor = jnp.minimum((count + 1.0) / (warmup_steps + 1.0), 1.0)
        return learning_rate * factor

    return schedule


def linear_warmup_cosine(learning_rate: float, warmup_steps: int,
                         decay_steps: int, final_fraction: float = 0.1):
    """Linear warmup (same +1 LambdaLR indexing as the reference) then
    cosine decay to ``final_fraction * learning_rate`` at ``decay_steps``
    (beyond-parity: the reference only has warmup-constant)."""

    def schedule(count):
        warm = jnp.minimum((count + 1.0) / (warmup_steps + 1.0), 1.0)
        span = jnp.maximum(decay_steps - warmup_steps, 1)
        progress = jnp.clip((count - warmup_steps) / span, 0.0, 1.0)
        cos = final_fraction + (1.0 - final_fraction) * 0.5 * (
            1.0 + jnp.cos(jnp.pi * progress))
        # during warmup progress clips to 0 and cos is exactly 1.0
        return learning_rate * warm * cos

    return schedule


def build_schedule(learning_rate: float, warmup_steps: int,
                   lr_schedule: str = "constant", decay_steps: int = 0):
    """Single source of truth for --lr-schedule resolution (the trainer's
    optimizer and the torch checkpoint exporter must agree on the current
    rate — checkpoint/convert.py)."""
    if lr_schedule == "cosine":
        return linear_warmup_cosine(learning_rate, warmup_steps,
                                    max(decay_steps, warmup_steps + 1))
    if lr_schedule == "constant":
        return linear_warmup_constant(learning_rate, warmup_steps)
    raise ValueError(f"unknown lr_schedule {lr_schedule!r}")
