"""Global-norm gradient clipping with a non-finite guard (ref: utils.py:58-63).

The reference uses ``torch.nn.utils.get_total_norm(error_if_nonfinite=True)``
followed by ``clip_grads_with_norm_`` — i.e. a NaN/Inf global gradient norm
*raises*, feeding the fault-handler path, and the clip coefficient is
``min(max_norm / (total_norm + 1e-6), 1.0)``.

In JAX the clip happens inside the jitted step (pure function of the grads);
the non-finite *raise* is a host-side decision made by the training loop when
it pulls the ``grad_norm`` metric (you cannot raise from inside ``jit``).
"""

import jax
import jax.numpy as jnp


class NonFiniteGradientError(RuntimeError):
    """Host-side equivalent of torch's ``error_if_nonfinite`` (ref: utils.py:61)."""


def global_norm(tree) -> jax.Array:
    """L2 norm over the concatenation of every leaf (torch ``get_total_norm``)."""
    leaves = jax.tree_util.tree_leaves(tree)
    return jnp.sqrt(
        sum(jnp.sum(jnp.square(leaf.astype(jnp.float32))) for leaf in leaves)
    )


def clip_grads_with_norm(grads, max_norm: float):
    """Scale ``grads`` by ``min(max_norm / (norm + 1e-6), 1.0)``.

    Returns ``(clipped_grads, total_norm)``; matches torch's
    ``clip_grads_with_norm_`` coefficient exactly (ref: utils.py:62).
    """
    total_norm = global_norm(grads)
    clip_coef = jnp.minimum(max_norm / (total_norm + 1e-6), 1.0)
    clipped = jax.tree_util.tree_map(
        lambda g: (g.astype(jnp.float32) * clip_coef).astype(g.dtype), grads
    )
    return clipped, total_norm
