"""Precision registry (ref: utils.py:14-19).

The reference constructs the model — and therefore the AdamW state — directly
in the selected dtype via a ``torch.set_default_dtype`` context manager
(ref: utils.py:100-110, train.py:54-55). JAX has no global default-dtype
switch; instead the dtype is threaded explicitly as ``param_dtype`` (weights,
and hence optimizer moments) and ``dtype`` (activations/compute) through the
Flax modules, which is the idiomatic equivalent.
"""

import jax.numpy as jnp

PRECISION_STR_TO_DTYPE = {
    "fp16": jnp.float16,
    "bf16": jnp.bfloat16,
    "fp32": jnp.float32,
    # fp64 requires `jax.config.update("jax_enable_x64", True)`; registered for
    # CLI parity with the reference registry.
    "fp64": jnp.float64,
}

DTYPE_TO_BYTES = {
    jnp.float16: 2,
    jnp.bfloat16: 2,
    jnp.float32: 4,
    jnp.float64: 8,
}
