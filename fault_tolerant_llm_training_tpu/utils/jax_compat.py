"""Version shims for jax APIs that moved between 0.4.x and 0.6+.

The build targets current jax (top-level ``jax.shard_map`` with
``axis_names=``/``check_vma=``); CI containers may carry a 0.4.x jaxlib
whose ``jax.experimental.shard_map`` spells the same partial-manual
contract as ``auto=`` (the COMPLEMENT of the manual axes) and
``check_rep=``. Everything else (mesh/in_specs/out_specs) is identical,
so one thin adapter keeps the call sites on the modern spelling.
"""

from typing import Optional, Set

import jax

try:  # jax >= 0.6 exposes shard_map at the top level
    from jax import shard_map as _new_shard_map
except ImportError:  # pragma: no cover
    _new_shard_map = None
    from jax.experimental.shard_map import shard_map as _old_shard_map


def shard_map(f, mesh, in_specs, out_specs,
              axis_names: Optional[Set] = None, check_vma: bool = True):
    """``jax.shard_map`` with the modern keywords on any supported jax."""
    if _new_shard_map is not None:
        kwargs = {} if axis_names is None else {"axis_names": axis_names}
        return _new_shard_map(f, mesh=mesh, in_specs=in_specs,
                              out_specs=out_specs, check_vma=check_vma,
                              **kwargs)
    auto = (frozenset(mesh.axis_names) - frozenset(axis_names)
            if axis_names is not None else frozenset())
    return _old_shard_map(f, mesh=mesh, in_specs=in_specs,
                          out_specs=out_specs, check_rep=check_vma,
                          auto=auto)
