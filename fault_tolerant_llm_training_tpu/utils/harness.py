"""Shared synthetic-workload harness for bench.py and scripts/profile_step.py.

One definition of "a training step on random data" so the benchmark and the
profiler measure the identical workload: same TrainState construction, same
optimizer, same label convention (shift-by-one with a -100 tail, matching
CollatorForCLM / ref dataset.py:44-53).
"""

from typing import Tuple

import numpy as np
import jax
import jax.numpy as jnp

from ..models import Transformer
from ..training.state import TrainState
from ..training.step import make_optimizer, make_train_step


def synthetic_state_and_step(cfg, mesh=None, learning_rate: float = 3e-4,
                             warmup_steps: int = 10,
                             grad_max_norm: float = 1.0,
                             grad_accum: int = 1):
    """Build (state, jitted step_fn) for ``cfg``.

    With ``mesh``, params/optimizer are laid out by the path-rule shardings
    (parallel/sharding.py) and the state argument is donated; without, a
    plain single-device jit.
    """
    model = Transformer(cfg)
    opt = make_optimizer(learning_rate, warmup_steps=warmup_steps)

    def init_fn(key):
        params = model.init(key, jnp.zeros((1, cfg.seq_len), jnp.int32))["params"]
        return TrainState(step=jnp.zeros((), jnp.int32), params=params,
                          opt_state=opt.init(params))

    step = make_train_step(model, opt, grad_max_norm, grad_accum=grad_accum)
    if mesh is None:
        state = jax.jit(init_fn)(jax.random.PRNGKey(0))
        return state, jax.jit(step, donate_argnums=(0,))

    from jax.sharding import NamedSharding
    from ..parallel.sharding import param_pspecs

    abstract = jax.eval_shape(init_fn, jax.random.PRNGKey(0))
    specs = param_pspecs(abstract)
    shardings = jax.tree_util.tree_map(
        lambda s: NamedSharding(mesh, s), specs,
        is_leaf=lambda x: isinstance(x, jax.sharding.PartitionSpec))
    state = jax.jit(init_fn, out_shardings=shardings)(jax.random.PRNGKey(0))
    step_fn = jax.jit(step, donate_argnums=(0,),
                      out_shardings=(shardings, None))
    return state, step_fn


def synthetic_batch(cfg, batch: int, seed: int = 0,
                    sharding=None) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Random (toks, labels) CLM batch; labels shift by one with a -100
    tail (the collator's convention, ref dataset.py:47-53)."""
    rng = np.random.default_rng(seed)
    toks = rng.integers(0, cfg.vocab_size,
                        (batch, cfg.seq_len)).astype(np.int32)
    if sharding is not None:
        toks = jax.device_put(toks, sharding)
    else:
        toks = jnp.asarray(toks)
    labels = jnp.concatenate(
        [toks[:, 1:], jnp.full((batch, 1), -100, jnp.int32)], axis=1)
    return toks, labels
