"""Throughput / MFU meters (reference has none — SURVEY.md §5.5 notes the gap;
the only reference metric is the loss line at train.py:115-116)."""

import time


class Throughput:
    """Steady-state tokens/sec and step-time tracker (excludes warmup steps)."""

    def __init__(self, tokens_per_step: int, warmup_steps: int = 2):
        self.tokens_per_step = tokens_per_step
        self.warmup_steps = warmup_steps
        self._seen = 0
        self._t0 = None
        self._steps = 0

    def step(self) -> None:
        self._seen += 1
        if self._seen == self.warmup_steps:
            self._t0 = time.perf_counter()
        elif self._seen > self.warmup_steps:
            self._steps += 1

    @property
    def steps_per_sec(self) -> float:
        if not self._steps or self._t0 is None:
            return 0.0
        return self._steps / (time.perf_counter() - self._t0)

    @property
    def tokens_per_sec(self) -> float:
        return self.steps_per_sec * self.tokens_per_step


def transformer_flops_per_token(n_params: int, seq_len: int, dim: int,
                                n_layers: int, causal: bool = False) -> float:
    """Model FLOPs per token, fwd+bwd: 6N matmul FLOPs plus attention
    score/value FLOPs — 12*L*S*d per token dense, halved under a causal
    mask (the kernels only compute the lower triangle). ``n_params``
    should EXCLUDE the input-embedding table when the embedding is a
    gather (no matmul FLOPs); the LM head does real matmuls and counts.
    This causal-masked, embed-excluded convention is the one behind every
    MFU figure in BASELINE.md."""
    attn = 12.0 * n_layers * dim * seq_len
    return 6.0 * n_params + (attn / 2.0 if causal else attn)


def mfu(tokens_per_sec: float, flops_per_token: float, peak_flops: float) -> float:
    return tokens_per_sec * flops_per_token / peak_flops


def device_memory_stats():
    """(bytes_in_use, bytes_limit) for device 0; (None, None) where the
    backend exposes no memory_stats (CPU; some remote transports)."""
    try:
        import jax
        stats = jax.local_devices()[0].memory_stats() or {}
    except Exception:
        return None, None
    return (stats.get("bytes_in_use"),
            stats.get("bytes_limit") or stats.get("bytes_reservable_limit"))


def hbm_usage_str() -> str:
    """'x.x/y.y GB' for device 0, or '' without backend memory stats."""
    used, limit = device_memory_stats()
    if used is None:
        return ""
    s = f"{used / 1e9:.1f}"
    return f"{s}/{limit / 1e9:.1f} GB" if limit else f"{s} GB"
