"""Throughput / MFU meters (reference has none — SURVEY.md §5.5 notes the gap;
the only reference metric is the loss line at train.py:115-116)."""

import time
from typing import List, Optional, Tuple


class Throughput:
    """Steady-state tokens/sec and step-time tracker (excludes warmup steps).

    ``reset(tag=...)`` restarts the warmup-exclusion window and tags the
    next measured window; the trainer calls it on ``ckpt_restore`` so the
    first post-resume tokens/s figure (a) excludes the restore/recompile
    wall from its denominator instead of mixing it into "steady state", and
    (b) carries a ``window='post_resume'`` label in the emitted metric so
    dashboards don't read the transient as a regression.
    """

    def __init__(self, tokens_per_step: int, warmup_steps: int = 2):
        self.tokens_per_step = tokens_per_step
        self.warmup_steps = warmup_steps
        self.window_tag: Optional[str] = None
        self._seen = 0
        self._t0 = None
        self._steps = 0

    def reset(self, tag: Optional[str] = None) -> None:
        """Restart the meter (fresh warmup window); ``tag`` labels the new
        window until :meth:`clear_tag`."""
        self._seen = 0
        self._t0 = None
        self._steps = 0
        self.window_tag = tag

    def clear_tag(self) -> None:
        self.window_tag = None

    def step(self) -> None:
        self._seen += 1
        if self._seen == self.warmup_steps:
            self._t0 = time.perf_counter()
        elif self._seen > self.warmup_steps:
            self._steps += 1

    @property
    def steps_per_sec(self) -> float:
        if not self._steps or self._t0 is None:
            return 0.0
        return self._steps / (time.perf_counter() - self._t0)

    @property
    def tokens_per_sec(self) -> float:
        return self.steps_per_sec * self.tokens_per_step


def transformer_flops_per_token(n_params: int, seq_len: int, dim: int,
                                n_layers: int, causal: bool = False) -> float:
    """Model FLOPs per token, fwd+bwd: 6N matmul FLOPs plus attention
    score/value FLOPs — 12*L*S*d per token dense, halved under a causal
    mask (the kernels only compute the lower triangle). ``n_params``
    should EXCLUDE the input-embedding table when the embedding is a
    gather (no matmul FLOPs); the LM head does real matmuls and counts.
    This causal-masked, embed-excluded convention is the one behind every
    MFU figure in BASELINE.md."""
    attn = 12.0 * n_layers * dim * seq_len
    return 6.0 * n_params + (attn / 2.0 if causal else attn)


def mfu(tokens_per_sec: float, flops_per_token: float, peak_flops: float) -> float:
    return tokens_per_sec * flops_per_token / peak_flops


V5E_BF16_PEAK = 197e12  # TPU v5e peak bf16 FLOP/s (public spec)


def device_peak_flops() -> Optional[float]:
    """Per-chip peak FLOP/s for MFU, or None off-TPU. Same convention as
    bench.py: the constant is v5e-specific, so MFU is only claimed on an
    actual TPU backend — a CPU 'MFU' against a TPU peak is noise."""
    try:
        import jax
        backend = jax.default_backend()
    except Exception:
        return None
    return V5E_BF16_PEAK if backend == "tpu" else None


def per_device_memory_stats() -> List[Tuple[str, Optional[int], Optional[int]]]:
    """``(device id string, bytes_in_use, bytes_limit)`` for every LOCAL
    device; empty where the backend exposes no memory_stats (CPU; some
    remote transports). Feeds the per-device HBM gauges in the metric
    registry — under pipeline/tensor sharding the devices are NOT
    symmetric (stage 0 holds the embedding, the last stage the LM head),
    and the loudest device is the one that OOMs."""
    try:
        import jax
        devices = jax.local_devices()
    except Exception:
        return []
    out = []
    for d in devices:
        try:
            stats = d.memory_stats() or {}
        except Exception:
            continue
        used = stats.get("bytes_in_use")
        limit = (stats.get("bytes_limit")
                 or stats.get("bytes_reservable_limit"))
        if used is None:
            continue
        out.append((str(getattr(d, "id", len(out))), used, limit))
    return out


def device_memory_stats():
    """(bytes_in_use, bytes_limit) of the most-loaded local device —
    max-over-devices, the binding constraint under pipeline/tensor sharding
    where per-device footprints differ (device 0 alone underestimates the
    OOM risk by up to a stage's worth of params). (None, None) where the
    backend exposes no memory_stats."""
    stats = per_device_memory_stats()
    if not stats:
        return None, None
    _, used, limit = max(stats, key=lambda s: s[1])
    return used, limit


def hbm_usage_str() -> str:
    """'x.x/y.y GB' for the most-loaded device, or '' without backend
    memory stats."""
    used, limit = device_memory_stats()
    if used is None:
        return ""
    s = f"{used / 1e9:.1f}"
    return f"{s}/{limit / 1e9:.1f} GB" if limit else f"{s} GB"
