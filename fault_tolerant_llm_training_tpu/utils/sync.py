"""Value-dependent device synchronization.

``jax.block_until_ready`` tracks buffer *readiness events*, which on tunneled
PJRT backends (the axon TPU client used here) can fire before the producing
computation finishes, so it cannot be used to bound in-flight work or to
delimit timing measurements there. Host *reads* of a value, by contrast, are
data-dependent on every backend — a device->host transfer waits for the
producing computation. (This also means correctness of downstream consumers
that read values, e.g. Orbax checkpoint serialization, never depends on this
barrier; it exists to drain dispatched work at a known point.)

``hard_sync`` combines both: it materializes every scalar (0-d) leaf — all
outputs of one XLA executable complete together, so for a tree produced by a
single jitted step (TrainState with its ``step`` counter, a metrics dict)
fetching one scalar output is an exact barrier for the whole tree — and then
calls ``block_until_ready`` on the rest, which is exact on non-tunneled
backends and covers leaves produced by other dispatches.
"""

import jax


def hard_sync(tree) -> None:
    """Drain the computation(s) producing ``tree`` (see module docstring)."""
    leaves = [x for x in jax.tree_util.tree_leaves(tree)
              if hasattr(x, "ndim") and getattr(x, "size", 0) > 0]
    scalars = [x for x in leaves if x.ndim == 0]
    if scalars:
        jax.device_get(scalars)
    elif leaves:
        # No scalar outputs: fetch one element of every leaf (leaves may come
        # from different dispatches) — still a value-dependent barrier,
        # unlike block_until_ready alone; one batched transfer.
        jax.device_get([x[(0,) * x.ndim] for x in leaves])
    jax.block_until_ready(tree)
