from .dtypes import PRECISION_STR_TO_DTYPE
from .logging import init_logger, logger
from .schedules import linear_warmup_constant
from .grad_clip import global_norm, clip_grads_with_norm
from .config import get_args, TrainConfig

__all__ = [
    "PRECISION_STR_TO_DTYPE",
    "init_logger",
    "logger",
    "linear_warmup_constant",
    "global_norm",
    "clip_grads_with_norm",
    "get_args",
    "TrainConfig",
]
