"""Config / flag system (ref: utils.py:112-203 + env at utils.py:11-12).

Every reference flag is kept with the same name, type, and default so that the
reference's ``TRAINING_CMD`` lines (ref: train.sh:16-27) parse unchanged.
TPU-specific flags (mesh shape, attention impl, checkpointing cadence, ...)
are additive.

Environment contract (ref: utils.py:11-12, train.py:16):
- ``WORKDIR``       — job working dir, used for self-resubmit (``sbatch $WORKDIR/train.sh``)
- ``SLURM_JOB_ID``  — names the checkpoint of *this* job (``checkpoint_{JOBID}``)
"""

import argparse
import dataclasses
import os
from typing import Optional

WORKDIR = os.getenv("WORKDIR", "")
JOBID = os.environ.get("SLURM_JOB_ID")


@dataclasses.dataclass
class TrainConfig:
    """Typed view over the parsed flags (the reference passes the raw Namespace)."""

    # --- reference flags (ref: utils.py:114-201) ---
    dataset: str = ""
    checkpoint_path: str = ""
    checkpoint_id: str = ""
    tokenizer_name_or_path: str = "unsloth/Mistral-Nemo-Base-2407-bnb-4bit"
    sequence_length: int = 4096
    batch_size: int = 1
    fused_optimizer: bool = False  # no-op on TPU: XLA fuses the optax update
    learning_rate: float = 1e-5
    lr_warmup_steps: int = 10
    training_steps: int = 1000
    logging_frequency: int = 5
    grad_max_norm: float = 1.0
    model_dtype: str = "bf16"
    compile: bool = False  # no-op on TPU: the train step is always jitted
    raise_error: bool = False  # legacy alias for --chaos "step=N:exception"
    error_step: int = 100
    # Declarative fault schedule (chaos/schedule.py grammar or a JSON file):
    # "step=<N>:<fault>[=<arg>][@rank=<R>];..." — seeded by --seed.
    chaos: str = ""
    # Restrict --raise-error to one process index (a host-LOCAL fault, the
    # pod fence's test shape); -1 = raise on every process (replicated,
    # the reference's single-process semantics).
    error_local_rank: int = -1
    # --- model selection (reference hard-codes Llama-3-8B in train.py:43-53) ---
    model: str = "gpt2-125m"
    vocab_size: int = 0  # 0 -> from tokenizer (ref: train.py:51)
    # --- TPU-native additions ---
    seed: int = 0
    dp: int = -1  # data-parallel mesh size; -1 = fill remaining devices
    fsdp: int = 1  # FSDP (param/optimizer sharding) mesh size
    tp: int = 1  # tensor-parallel mesh size
    sp: int = 1  # sequence-parallel (ring attention) mesh size
    pp: int = 1  # pipeline-parallel mesh size (needs --layer-impl scan)
    microbatches: int = 0  # pipeline microbatches (0 = one per stage)
    pp_schedule: str = "1f1b"  # 1f1b (O(pp) activation memory) | gpipe
    pp_stage_unroll: bool = True  # unroll each stage's layer loop (models/configs.py)
    ep: int = 1  # expert-parallel mesh size (needs an MoE model)
    # MoE overrides; None = keep the model preset's values
    moe_experts: Optional[int] = None
    moe_top_k: Optional[int] = None
    moe_capacity_factor: Optional[float] = None
    moe_aux_weight: Optional[float] = None
    moe_impl: Optional[str] = None
    attention_impl: str = "auto"  # auto | xla | pallas | ring
    sp_layout: str = "zigzag"  # zigzag (causal-balanced ring) | contiguous
    embed_impl: str = "auto"  # auto | gather | one_hot (one_hot: TP-friendly)
    layer_impl: str = "loop"  # loop | scan (scan: O(1) compile time in depth)
    remat: bool = False  # jax.checkpoint each block (trade FLOPs for HBM)
    master_weights: str = "same"  # same | fp32 (fp32 optimizer master copy)
    data_loading: str = "map"  # map (ParquetDataset path) | packed (iterable)
    # Pod data path: host = each process tokenizes only its own devices'
    # batch rows (map path; O(1) in host count); replicated = every host
    # builds the full global batch; auto = host on pods, replicated alone.
    data_sharding: str = "auto"
    shuffle: bool = False  # seeded per-epoch shuffle (default: reference's strict doc order)
    # exact = np.permutation per epoch (O(corpus) memory per host);
    # feistel = keyed bijection computed per sample (O(1) memory — the
    # pod-scale form; resume state is identical in shape either way)
    shuffle_impl: str = "exact"
    pretokenize_dir: str = ""  # cache dir for one-time tokenization (map path)
    legacy_packing: bool = True  # reproduce reference packing quirks (dataset.py:78,93)
    checkpoint_frequency: int = 0  # 0 = fault-triggered only (reference behavior)
    checkpoint_keep: int = 2  # Orbax max_to_keep (older steps GC'd)
    # Deployment loop (deploy/): after each periodic save's integrity
    # manifest commits, host 0 atomically points published.json at the
    # step so a --follow serving process hot-reloads it.
    publish: bool = False
    eval_dataset: str = ""  # held-out parquet; empty = use --dataset
    eval_frequency: int = 0  # evaluate every N steps (0 = off)
    eval_batches: int = 8  # batches per evaluation pass
    prefetch: int = 2  # host->device prefetch depth (reference has none)
    inflight: int = 2  # max dispatched-but-unfinished steps (bounds signal latency)
    grad_accum: int = 1  # gradient-accumulation slices per step (memory/batch)
    lr_schedule: str = "constant"  # constant (reference) | cosine
    lr_decay_steps: int = 0  # cosine horizon (0 = --training-steps)
    # Multihost: steps between cluster-wide signal agreements. The
    # agreement is a host-side KV-store round (ft/multihost.py) — it no
    # longer drains the dispatch pipeline, but it is still a cluster
    # rendezvous (every host waits for the slowest), so every N steps
    # bounds signal latency to N*step_time (vs the 120 s USR1 lead)
    # without paying the rendezvous each step.
    signal_sync_frequency: int = 5
    # Bound (seconds) on every blocking multihost wait (metric fetch, the
    # KV signal-agreement round, fence stop-gather, pre-save barrier/
    # drain; the collective checkpoint write uses a derived, larger
    # bound). A wait outliving it with a peer-fault announcement pending
    # routes to the fault fence; with none, the peer is presumed dead and
    # the host degrades to a clean no-save exit 0. Must exceed the
    # slowest legitimate step + drain on the target pod.
    peer_timeout_seconds: float = 300.0
    # The scheduler's pre-termination warning lead (seconds): Slurm arms
    # SIGUSR1 this long before the time limit (ref train.sh:12,
    # --signal=USR1@120). The trainer checks its estimated checkpoint
    # save time against this budget at startup (checkpoint/manager.py).
    signal_lead_seconds: int = 120
    profile_dir: str = ""  # jax.profiler trace output; "" = off
    # Windowed profiler capture "A:B" (steps A..B inclusive; obs/trace.py).
    # Traces land in --profile-dir (or <checkpoint-path>/traces when unset).
    # Unlike bare --profile-dir, the capture is bounded — usable mid-run on
    # long jobs.
    trace_steps: str = ""
    # Reactive profiler window (obs/trace.py AutoTraceWindow): arm a
    # bounded capture automatically, once per run, when a step's wall
    # time exceeds 2x the rolling median. Ignored when --trace-steps is
    # set (one profiler owner at a time).
    auto_trace: bool = False
    # Structured JSONL flight-recorder output dir (obs/events.py); "" =
    # <checkpoint-path>/events, "off" = disabled. One events_<jobid>.jsonl
    # per job; scripts/goodput_report.py stitches them across restarts.
    event_log_dir: str = ""
    # Serve the metric registry at http://host:PORT/metrics (Prometheus
    # text format, obs/prometheus.py); 0 = off.
    metrics_port: int = 0
    # Per-host heartbeat publish interval through the ft/multihost.py KV
    # store (exported as ftl_host_heartbeat_* gauges); 0 = off. Every
    # host publishes and sweeps regardless of --metrics-port — the age
    # gauges also feed the flight recorder, not just a scraper.
    heartbeat_seconds: float = 10.0
    # JAX persistent compilation cache directory (utils/compile_cache.py);
    # "" = off. A warm cache turns the restart-after-preemption compile
    # into a disk read — the build time lands in the flight recorder
    # either way, so goodput reports show cold vs warm directly.
    compile_cache_dir: str = ""
    resubmit_command: str = ""  # override for tests; default: sbatch $WORKDIR/train.sh
    distributed: bool = False  # call jax.distributed.initialize() (multi-host pods)

    def event_log_path(self, job_id: str) -> str:
        """Resolved flight-recorder path for this job; '' = disabled."""
        if self.event_log_dir == "off":
            return ""
        base = self.event_log_dir or (
            os.path.join(self.checkpoint_path, "events")
            if self.checkpoint_path else "")
        return os.path.join(base, f"events_{job_id}.jsonl") if base else ""


def get_args(argv: Optional[list] = None) -> TrainConfig:
    """Parse flags. Mirrors ref utils.py:112-203 plus TPU additions."""
    parser = argparse.ArgumentParser(description="TPU-native fault-tolerant LLM training")
    # --- reference flag set, names/defaults preserved (ref: utils.py:114-201) ---
    parser.add_argument(
        "--dataset",
        type=str,
        default=os.path.join(WORKDIR, "data", "train_data.parquet") if WORKDIR else "",
        help="Parquet source with a 'text' column: one file, a directory "
             "of *.parquet shards, or a glob pattern",
    )
    parser.add_argument(
        "--checkpoint-path",
        type=str,
        default=f"{WORKDIR}/checkpoints",
        help="Directory where checkpoints are saved/loaded",
    )
    parser.add_argument(
        "--checkpoint-id",
        type=str,
        default="",
        help="Job id whose checkpoint_{id} directory to resume from",
    )
    parser.add_argument(
        "--tokenizer-name-or-path",
        type=str,
        default="unsloth/Mistral-Nemo-Base-2407-bnb-4bit",
        help="HF tokenizer name/path, or 'byte' for the built-in offline byte tokenizer",
    )
    parser.add_argument("--sequence-length", type=int, default=4096)
    parser.add_argument("--batch-size", type=int, default=1)
    parser.add_argument(
        "--fused-optimizer",
        action="store_true",
        help="Accepted for CLI parity; XLA always fuses the optimizer update on TPU",
    )
    parser.add_argument("--learning-rate", type=float, default=1e-5)
    parser.add_argument("--lr-warmup-steps", type=int, default=10)
    parser.add_argument("--training-steps", type=int, default=1000)
    parser.add_argument("--logging-frequency", type=int, default=5,
                        help="Log every --logging-frequency steps")
    parser.add_argument("--grad-max-norm", type=float, default=1)
    parser.add_argument("--model-dtype", type=str, default="bf16",
                        help="Dtype for parameters, gradients and optimizer states")
    parser.add_argument(
        "--compile",
        action="store_true",
        help="Accepted for CLI parity; the train step is always jitted on TPU",
    )
    parser.add_argument("--raise-error", action="store_true",
                        help="Raise an error in the training loop at "
                             "--error-step (legacy alias for --chaos "
                             "'step=N:exception')")
    parser.add_argument("--chaos", type=str, default="",
                        help="Declarative fault schedule: "
                             "'step=<N>:<fault>[=<arg>][@rank=<R>]' entries "
                             "separated by ';' (faults: sigusr1, sigterm, "
                             "exception, ckpt_corrupt, loader_stall, "
                             "kv_delay, kv_fail), or a JSON schedule file "
                             "path. Injections are seeded by --seed.")
    parser.add_argument("--error-step", type=int, default=100,
                        help="Step at which to raise an error if --raise-error is set")
    parser.add_argument("--error-local-rank", type=int, default=-1,
                        help="Raise the --raise-error injection only on "
                             "this process index (a host-local fault, "
                             "exercising the pod fault fence); -1 = all "
                             "processes")
    # --- model selection ---
    parser.add_argument("--model", type=str, default="gpt2-125m",
                        help="Model preset: gpt2-125m | llama3-8b | tiny")
    parser.add_argument("--vocab-size", type=int, default=0,
                        help="0 = take vocab size from the tokenizer")
    # --- TPU-native additions ---
    parser.add_argument("--seed", type=int, default=0)
    parser.add_argument("--dp", type=int, default=-1, help="data-parallel size (-1: infer)")
    parser.add_argument("--fsdp", type=int, default=1, help="FSDP shard size")
    parser.add_argument("--tp", type=int, default=1, help="tensor-parallel size")
    parser.add_argument("--sp", type=int, default=1, help="sequence-parallel (ring) size")
    parser.add_argument("--pp", type=int, default=1,
                        help="pipeline-parallel size (needs --layer-impl scan)")
    parser.add_argument("--microbatches", type=int, default=0,
                        help="pipeline microbatches (0 = one per stage)")
    parser.add_argument("--pp-schedule", type=str, default="1f1b",
                        choices=["1f1b", "gpipe"],
                        help="pipeline schedule: 1f1b interleaves each "
                             "microbatch's backward (O(pp) activation "
                             "memory); gpipe stores all microbatches")
    parser.add_argument("--no-pp-stage-unroll", dest="pp_stage_unroll",
                        action="store_false",
                        help="Scan (rather than unroll) each pipeline "
                             "stage's layer loop: O(1) compile time in "
                             "stage depth, ~22%% slower (the unrolled "
                             "default's pattern measured on-chip, "
                             "BASELINE.md round 4)")
    parser.add_argument("--ep", type=int, default=1,
                        help="expert-parallel size (needs an MoE model, "
                             "e.g. --model tiny-moe or --moe-experts N)")
    parser.add_argument("--moe-experts", type=int, default=None,
                        help="Mixture-of-Experts expert count (overrides "
                             "the preset; 0 = dense FFN)")
    parser.add_argument("--moe-top-k", type=int, default=None)
    parser.add_argument("--moe-capacity-factor", type=float, default=None)
    parser.add_argument("--moe-aux-weight", type=float, default=None,
                        help="weight of the router load-balancing loss")
    parser.add_argument("--moe-impl", type=str, default=None,
                        choices=["auto", "capacity", "sorted"],
                        help="MoE dispatch: capacity = GShard slots (drops "
                             "overflow, expert-parallel capable); sorted = "
                             "dropless ragged-dot grouped GEMMs")
    parser.add_argument("--attention-impl", type=str, default="auto",
                        choices=["auto", "xla", "pallas", "ring"])
    parser.add_argument("--sp-layout", type=str, default="zigzag",
                        choices=["zigzag", "contiguous"],
                        help="Sequence layout under --sp: zigzag balances "
                             "causal work around the ring (~2x fewer FLOPs)")
    parser.add_argument("--embed-impl", type=str, default="auto",
                        choices=["auto", "gather", "one_hot"],
                        help="Token-embedding lookup; one_hot contracts a "
                             "vocab-sharded table on the MXU (auto: one_hot "
                             "iff tensor-parallel)")
    parser.add_argument("--layer-impl", type=str, default="loop",
                        choices=["loop", "scan"],
                        help="Trunk form: loop unrolls each block; scan "
                             "compiles one block body over layer-stacked "
                             "params (O(1) compile time in depth)")
    parser.add_argument("--remat", action="store_true",
                        help="Rematerialize each transformer block (saves HBM)")
    parser.add_argument("--master-weights", type=str, default="same",
                        choices=["same", "fp32"])
    parser.add_argument("--data-loading", type=str, default="map",
                        choices=["map", "packed"])
    parser.add_argument("--data-sharding", type=str, default="auto",
                        choices=["auto", "host", "replicated"],
                        help="host: each process tokenizes only the batch "
                             "rows its devices consume (map path; removes "
                             "the O(hosts) redundant-tokenization cliff); "
                             "replicated: every host builds the full "
                             "batch; auto: host on multi-process runs")
    parser.add_argument("--shuffle", action="store_true",
                        help="Deterministic per-epoch data shuffling keyed "
                             "on --seed; iterator state stays a single "
                             "position, so bit-exact O(1) resume is "
                             "preserved (the reference trains in strict "
                             "document order, which produces order "
                             "artifacts in multi-epoch runs)")
    parser.add_argument("--shuffle-impl", type=str, default="exact",
                        choices=["exact", "feistel"],
                        help="exact: np.permutation per epoch (O(corpus) "
                             "host memory); feistel: keyed 4-round Feistel "
                             "bijection per sample (O(1) memory, the "
                             "pod-scale form; each row still appears "
                             "exactly once per epoch)")
    parser.add_argument("--pretokenize-dir", type=str, default="",
                        help="Tokenize the corpus once into a memmap cache "
                             "here; steady-state loading becomes a row "
                             "read (map path only). On multi-host pods this "
                             "MUST be on a filesystem shared by all hosts: "
                             "process 0 builds, the others poll for the "
                             "finished cache file")
    parser.add_argument("--no-legacy-packing", dest="legacy_packing",
                        action="store_false",
                        help="Fix the reference packing quirks (buffer discard / doc re-read)")
    parser.add_argument("--checkpoint-frequency", type=int, default=0,
                        help="Save every N steps; 0 = fault-triggered only (reference behavior)")
    parser.add_argument("--checkpoint-keep", type=int, default=2,
                        help="Orbax max_to_keep: retained checkpoint steps "
                             "(older ones are garbage-collected). Raise it "
                             "when --publish serves older steps (a "
                             "published step must outlive the pointer)")
    parser.add_argument("--publish", action="store_true",
                        help="After each periodic save's integrity manifest "
                             "commits, atomically point published.json at "
                             "the step (deploy/publish.py, host 0) so a "
                             "serve.py --follow process hot-reloads it")
    parser.add_argument("--eval-dataset", type=str, default="",
                        help="Held-out parquet (file/dir/glob) for --eval-frequency; "
                             "empty = evaluate on --dataset")
    parser.add_argument("--eval-frequency", type=int, default=0,
                        help="Evaluate every N steps (0 = off)")
    parser.add_argument("--eval-batches", type=int, default=8,
                        help="Batches per evaluation pass")
    parser.add_argument("--lr-schedule", type=str, default="constant",
                        choices=["constant", "cosine"],
                        help="constant = the reference's warmup-constant "
                             "LambdaLR; cosine decays to 10 percent over "
                             "--lr-decay-steps")
    parser.add_argument("--lr-decay-steps", type=int, default=0,
                        help="cosine decay horizon (0 = --training-steps)")
    parser.add_argument("--grad-accum", type=int, default=1,
                        help="Accumulate gradients over N batch slices per "
                             "step (token-weighted; peak activation memory "
                             "drops ~N-fold)")
    parser.add_argument("--prefetch", type=int, default=2)
    parser.add_argument("--inflight", type=int, default=2)
    parser.add_argument("--signal-sync-frequency", type=int, default=5)
    parser.add_argument("--peer-timeout-seconds", type=float, default=300.0,
                        help="Watchdog bound on blocking multihost waits; "
                             "on expiry the host either routes a peer's "
                             "announced fault to the fence or, with no "
                             "announcement, presumes the peer dead and "
                             "exits 0 cleanly without a checkpoint")
    parser.add_argument("--signal-lead-seconds", type=int, default=120,
                        help="scheduler pre-termination warning lead (the "
                             "USR1@N contract); the startup checkpoint-"
                             "budget check warns when the estimated save "
                             "exceeds it")
    parser.add_argument("--profile-dir", type=str, default="")
    parser.add_argument("--trace-steps", type=str, default="",
                        help="Windowed jax.profiler capture 'A:B' (steps A "
                             "through B inclusive, obs/trace.py); bounded, "
                             "so usable mid-run on long jobs. Output: "
                             "--profile-dir or <checkpoint-path>/traces")
    parser.add_argument("--auto-trace", action="store_true",
                        help="Arm a bounded profiler capture automatically "
                             "(once per run) when a step's wall time "
                             "regresses past 2x the rolling median "
                             "(obs/trace.py AutoTraceWindow); ignored when "
                             "--trace-steps is set")
    parser.add_argument("--event-log-dir", type=str, default="",
                        help="Flight-recorder JSONL dir (obs/events.py): "
                             "one events_<jobid>.jsonl per job, stitched "
                             "across restarts by scripts/goodput_report.py."
                             " '' = <checkpoint-path>/events, 'off' = "
                             "disabled")
    parser.add_argument("--metrics-port", type=int, default=0,
                        help="Serve Prometheus /metrics on this port "
                             "(obs/prometheus.py); 0 = off")
    parser.add_argument("--heartbeat-seconds", type=float, default=10.0,
                        help="Per-host heartbeat publish interval (KV "
                             "store; ftl_host_heartbeat_* gauges); 0 = off")
    parser.add_argument("--compile-cache-dir", type=str, default="",
                        help="JAX persistent compilation cache directory; "
                             "'' = off. Warm restarts skip the train-step "
                             "XLA compile; build time is logged cold vs "
                             "warm through the flight recorder")
    parser.add_argument("--resubmit-command", type=str, default="",
                        help="Override the self-resubmit command (tests); "
                             "default: sbatch $WORKDIR/train.sh $SLURM_JOB_ID")
    parser.add_argument("--distributed", action="store_true",
                        help="jax.distributed.initialize() for multi-host pods")
    args = parser.parse_args(argv)
    fields = {f.name for f in dataclasses.fields(TrainConfig)}
    return TrainConfig(**{k: v for k, v in vars(args).items() if k in fields})
