"""Device-property queries backing the kernel/dispatch budgets.

Round-3 review (VERDICT weak #5) flagged that the dispatch budgets were
hardcoded for the 16 GB v5e this framework was calibrated on — a v5p/v6e
(95 GB HBM) would engage the fused head+CE at the wrong footprint. The
budgets now derive from the runtime's device properties with the
calibration platform's values as the fallback:

- ``device_hbm_bytes`` — per-device accelerator memory, from
  ``Device.memory_stats()['bytes_limit']`` (consumers: ops/fused_ce.py
  ``auto_min_bytes``).
- The scoped-VMEM limit has no runtime query; ops/flash_attention.py
  documents it per-generation and reads the ``FTL_SCOPED_VMEM_KIB`` env
  override (matching XLA's ``--xla_tpu_scoped_vmem_limit_kib``).
"""

import functools


@functools.lru_cache(maxsize=None)
def device_hbm_bytes(default: int = 16 * 2**30) -> int:
    """Per-device accelerator memory in bytes.

    Reads ``bytes_limit`` from the first local device's ``memory_stats()``
    (the allocator's usable budget — slightly under the marketing HBM
    size, which is the number that matters for OOM dispatch decisions).
    Falls back to ``default`` — v5e's 16 GB, the platform every budget in
    this repo was calibrated on — when the backend exposes no stats (CPU,
    some plugin backends)."""
    import jax

    try:
        stats = jax.local_devices()[0].memory_stats() or {}
        limit = int(stats.get("bytes_limit", 0))
        if limit > 0:
            return limit
    except Exception:
        pass
    return default
