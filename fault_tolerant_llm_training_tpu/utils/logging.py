"""Logging + machine-checkable audit strings (ref: utils.py:10,21-29).

The reference's log strings are effectively the system's verification API —
its README asserts fault-tolerance correctness by grepping the Slurm ``.out``
files for the ``[EXIT HANDLER]`` audit trail and the resume breadcrumbs
(ref: utils.py:68,71,73,81,86,88,90; train.py:81). We keep those strings
byte-identical so the same checks (and our tests) work unchanged.
"""

import logging
import sys

logger = logging.getLogger()


def init_logger(level: int = logging.INFO) -> None:
    """Root logger -> stdout with the reference's format (ref: utils.py:21-29)."""
    logger.setLevel(level)
    logger.handlers.clear()  # absl/jax may have installed a basicConfig handler
    ch = logging.StreamHandler(sys.stdout)
    ch.setLevel(level)
    formatter = logging.Formatter("%(asctime)s - %(name)s - %(levelname)s - %(message)s")
    ch.setFormatter(formatter)
    logger.addHandler(ch)
    # Orbax/absl INFO chatter would drown the audit trail the .out files are
    # grepped for (SURVEY.md §4.3).
    logging.getLogger("absl").setLevel(logging.WARNING)


# --- Audit strings (byte-identical to the reference where behavior matches) ---
# ref: utils.py:68
AUDIT_CANCELLED = "[EXIT HANDLER] Job cancelled, terminating."
# ref: utils.py:71
AUDIT_TIMEOUT_SAVING = "[EXIT HANDLER] Job timed out, saving checkpoint."
# ref: utils.py:73
AUDIT_ERROR_SAVING = "[EXIT HANDLER] Error during training encountered, saving checkpoint."
# ref: utils.py:81 (formatted with the step)
AUDIT_SAVED_FMT = "[EXIT HANDLER] Checkpoint saved at step {step}"
# ref: utils.py:86
AUDIT_REQUEUE_FAILED_FMT = "[EXIT HANDLER] Failed to requeue job {job_id}."
# ref: utils.py:88
AUDIT_REQUEUED = "[EXIT HANDLER] sbatch requeued, new job will load the last checkpoint"
# ref: utils.py:90
AUDIT_UNKNOWN_FMT = "[EXIT HANDLER] Unknown exit signal {type}, terminating."
# ref: train.py:81
AUDIT_RESUME_FMT = "Resuming training from training_step {step}"
# ref: train.py:84
AUDIT_START = "Starting training!"
# ref: train.py:118
AUDIT_COMPLETED = "Training completed"
# ref: train.py:116 (formatted)
AUDIT_STEP_FMT = "Training step: {step} | Loss: {loss:.2f}"

# --- Serving audit strings (inference/serve.py) — same grep-the-.out-file
# discipline as the training trail: the drain lifecycle is asserted by
# tests/test_inference.py exactly like the exit-handler strings above. ---
AUDIT_SERVE_START = "Starting serving!"
AUDIT_SERVE_READY_FMT = ("Serving ready | model {model} | checkpoint step "
                         "{step} | slots {slots}")
AUDIT_SERVE_STEP_FMT = ("Serve step: {step} | Active: {active} | "
                        "Queued: {queued} | Done: {done}")
AUDIT_SERVE_DRAINING_FMT = ("[EXIT HANDLER] Signal {signum} received, "
                            "draining {active} in-flight request(s), "
                            "admission stopped.")
AUDIT_SERVE_DRAINED_FMT = ("[EXIT HANDLER] Drained; {completed} request(s) "
                           "completed, {queued} queued request(s) not "
                           "admitted.")
AUDIT_REQUEST_DONE_FMT = ("Request {id} done | {reason} | prompt "
                          "{prompt_tokens} tok | generated {new_tokens} tok "
                          "| ttft {ttft_ms:.0f} ms | {tps:.1f} tok/s")
AUDIT_SERVE_COMPLETED = "Serving completed"
AUDIT_SERVE_PREFIX_FMT = ("Prefix cache | lookups {lookups} | hit rate "
                          "{rate:.3f} | hit tokens {hit_tokens} | cached "
                          "blocks {cached} | cow copies {cow} | evictions "
                          "{evictions}")
AUDIT_SERVE_PREFILL_FMT = ("Packed prefill | rounds {rounds} | rows {rows} "
                           "| occupancy {occupancy:.3f} | inplace chunks "
                           "{inplace} | gather chunks {gather}")
AUDIT_SERVE_TREE_SPEC_FMT = ("Tree spec | shape {shape} | rounds {rounds} "
                             "| nodes {nodes} | accepted/round "
                             "{per_round:.2f} | branch util {util:.3f}")
AUDIT_KV_LEAK_FMT = ("[KV LEAK] {pool} pool: {leaked} block(s) leaked "
                     "after drain ({used} allocated, {cached} "
                     "prefix-cached)")

# --- Chaos + checkpoint-integrity audit trail (chaos/injector.py,
# checkpoint/manager.py) — same contract: these strings are what
# scripts/chaos_campaign.py and tests/test_chaos.py grep for, frozen in
# tests/test_audit_contract.py like the rest. ---
AUDIT_CHAOS_INJECT_FMT = "[CHAOS] Injected {fault} at step {step}"
AUDIT_TRACE_AUTO_FMT = ("[TRACE] Step time regressed {ratio:.1f}x vs "
                        "rolling median; capturing profiler window at "
                        "step {step}")
AUDIT_CKPT_VERIFY_FAILED_FMT = ("[CKPT VERIFY] Checkpoint step {step} "
                                "failed integrity check: {detail}")
AUDIT_CKPT_FALLBACK_FMT = ("[CKPT VERIFY] Falling back to checkpoint step "
                           "{step} (newest passing)")
AUDIT_CKPT_PARTIAL_SKIPPED_FMT = ("[CKPT FINALIZE] Skipped partial "
                                  "checkpoint directory {name}")

# --- Deployment-loop audit trail (deploy/publish.py, deploy/reload.py) —
# the continuous train->serve loop's grep surface: publishes, hot weight
# swaps and rejected (corrupt) publishes are asserted by
# tests/test_deploy.py and scripts/chaos_campaign.py exactly like the
# drain lifecycle above. ---
AUDIT_PUBLISH_FMT = ("[DEPLOY] Published checkpoint step {step} "
                     "(digest {digest})")
AUDIT_RELOAD_FMT = ("[DEPLOY] Weights reloaded: step {old} -> {new} | "
                    "{active} in-flight | swap {ms:.0f} ms")
AUDIT_RELOAD_REJECTED_FMT = ("[DEPLOY] Publish of step {step} rejected: "
                             "{detail}; serving continues on step "
                             "{current}")

# --- Serving-fleet audit trail (inference/fleet.py, inference/router.py) —
# membership and migration lifecycle: hosts audit their own join/leave,
# the router audits dead verdicts and migrations. scripts/chaos_campaign.py's
# fleet scenario and tests/test_fleet.py grep these, frozen in
# tests/test_audit_contract.py like the rest. ---
AUDIT_FLEET_JOIN_FMT = ("[FLEET] Host {host} joined: {slots} slot(s), "
                        "{blocks} free block(s), lease ttl {ttl:.1f}s")
AUDIT_FLEET_LEAVE_FMT = "[FLEET] Host {host} left ({reason})"
AUDIT_FLEET_DEAD_FMT = ("[FLEET] Host {host} declared dead: lease age "
                        "{age:.1f}s > ttl {ttl:.1f}s; fencing and "
                        "migrating {inflight} in-flight request(s)")
AUDIT_FLEET_MIGRATE_FMT = ("[FLEET] Migrating request {id}: {src} -> {dst} "
                           "(gen {gen}, {committed} committed token(s) "
                           "replayed)")
AUDIT_FLEET_REQUEUE_FMT = ("[FLEET] Requeued request {id} to the journal "
                           "({committed} committed token(s), reason "
                           "{reason})")

# --- Request-latency audit trail (inference/serve.py, inference/fleet.py) —
# the drain summary prints one per-request latency verdict so operators
# (and scripts/chaos_campaign.py) can grep TTFT/TPOT off the .out file;
# obs/reqtrace.py holds the machine-readable span trail behind it. ---
AUDIT_LATENCY_FMT = ("[LATENCY] Request {id} | trace {trace} | ttft "
                     "{ttft_ms:.0f} ms | tpot {tpot_ms:.2f} ms | "
                     "{tokens} tok | {reason}")

# --- Tiered-KV audit trail (inference/scheduler.py spill tier,
# inference/fleet.py + router.py block-shipment handoff) — every block
# movement across tiers is audited: spill exports, verified restores,
# CRC rejects (which fall back to the bit-exact committed-prefix
# replay), and handoff shipments. scripts/chaos_campaign.py's tiered
# scenario and tests/test_kv_tier.py grep these, frozen in
# tests/test_audit_contract.py like the rest. ---
AUDIT_KV_TIER_FMT = ("[KV TIER] Spill {action} request {id}: {blocks} "
                     "block(s), {bytes} byte(s) (tier={tier})")
AUDIT_HANDOFF_FMT = ("[HANDOFF] Block-shipment {action} request {id} "
                     "(gen {gen}): {blocks} block(s), {detail}")

# --- Quantized-KV audit trail (inference/serve.py, inference/fleet.py) —
# the drain summary's --kv-dtype receipt: what the pool stored its blocks
# as, the bytes one block costs (scale rows included), and the capacity
# ratio against the bf16 layout at the same geometry. Emitted for every
# paged engine (bf16 reads ratio 1.00), so the line is always on the
# grep surface; frozen in tests/test_audit_contract.py like the rest. ---
AUDIT_KV_QUANT_FMT = ("[KV QUANT] dtype={dtype} | {bytes_per_block} "
                      "B/block ({ratio:.2f}x vs bf16) | {blocks_total} "
                      "pool block(s)")

# --- Disaggregated prefill/decode audit trail (inference/scheduler.py,
# inference/router.py, inference/fleet.py) — the prefill->decode pipeline's
# grep surface: every incremental block shipment a prefill engine exports,
# every verified/rejected import on a decode engine, and the router's
# role-aware placements (including the placement-time mixed-dtype
# rejection). scripts/chaos_campaign.py's disagg scenario and
# tests/test_disagg.py grep these, frozen in tests/test_audit_contract.py
# like the rest. ---
AUDIT_DISAGG_SHIP_FMT = ("[DISAGG] Shipment {action} request {id} seq "
                         "{seq} (gen {gen}): blocks [{start}, {end}), "
                         "{detail}")
AUDIT_DISAGG_PLACE_FMT = ("[DISAGG] Placement {action} request {id} "
                          "(gen {gen}): {detail}")

# --- Fleet-global KV store audit trail (inference/kvstore.py via
# inference/scheduler.py) — the content-addressed block store's grep
# surface: publishes of committed prefix trains, verified cross-host
# fetches with their hit depth, CRC rejects (which degrade to local
# chunked prefill), and the sweeper's LRU evictions. The campaign's
# kvstore scenario and tests/test_kv_store.py grep these, frozen in
# tests/test_audit_contract.py like the rest. ---
AUDIT_KV_STORE_FMT = ("[KV STORE] {action} key {key} request {id}: "
                      "{blocks} block(s), {detail}")

# --- KV transport audit trail (inference/transport.py via
# inference/scheduler.py) — the pluggable block-train lane's grep
# surface: mem-lane pushes riding each shipment/publish export, which
# lane a train actually landed on, lane fallbacks (a mem metadata
# mismatch degrading to the fs artifact, the fs CRC reject degrading to
# replay), partial store hits, and paced prefill admissions. The
# campaign's transport scenario and tests/test_transport.py grep these,
# frozen in tests/test_audit_contract.py like the rest. ---
AUDIT_KV_XPORT_FMT = ("[KV XPORT] {action} lane {lane} request {id}: "
                      "{blocks} block(s), {detail}")

# --- Fleet-wide observability plane audit trail (obs/federate.py,
# scripts/fleet_timeline.py, scripts/bench_trend.py) — the aggregation
# layer's grep surface: each federation sweep (hosts scraped, series
# re-exported, fleet rollups derived), each HLC-ordered timeline fold
# with its anomaly count, and the bench-regression sentinel's verdict.
# ci_nightly's federation drill and tests/test_fleetscope.py grep these,
# frozen in tests/test_audit_contract.py like the rest. ---
AUDIT_FLEETSCOPE_FEDERATE_FMT = ("[FLEETSCOPE] Federated {hosts} host(s): "
                                 "{series} series, {rollups} fleet "
                                 "rollup(s), {stale} stale, {failures} "
                                 "scrape failure(s)")
AUDIT_FLEETSCOPE_TIMELINE_FMT = ("[FLEETSCOPE] Timeline: {events} event(s) "
                                 "from {hosts} host(s) in HLC order, "
                                 "{anomalies} anomalie(s)")
AUDIT_FLEETSCOPE_TREND_OK_FMT = ("[FLEETSCOPE] Bench trend: {metrics} "
                                 "pinned metric(s) across {receipts} "
                                 "receipt(s) within {tolerance_pct}% of "
                                 "baseline")
AUDIT_FLEETSCOPE_TREND_REGRESSION_FMT = ("[FLEETSCOPE] Bench trend "
                                         "REGRESSION: {receipt} "
                                         "{metric} {delta_pct:+.1f}% "
                                         "({baseline} -> {current}, "
                                         "{direction} is better)")

# --- Multi-tenant adapter serving audit trail (inference/adapters.py via
# scheduler/serve/fleet) — one action-shaped line for the adapter pool's
# lifecycle (page-in, evict, swap, reject) and a drain summary mirroring
# the prefix-cache line. FROZEN; pinned by tests/test_audit_contract.py.
AUDIT_ADAPTER_FMT = ("[ADAPTER] {action} adapter {name}: {pages} page(s), "
                     "{detail}")
AUDIT_ADAPTER_SUMMARY_FMT = ("[ADAPTER] drain summary | served {served} "
                             "adapter(s) | page-ins {pageins} | evictions "
                             "{evictions} | resident {resident_bytes} "
                             "byte(s) | rejects {rejects}")
