// Native host-side data-pipeline hot path.
//
// The reference framework's data layer is pure Python (ref: dataset.py) and
// leans on the PyTorch container for native speed; at TPU step rates the
// host-side batch assembly (tokenize -> pack -> shift -> mask) becomes the
// bottleneck (SURVEY.md §7.3 #5). These kernels do the per-batch O(B*S) work
// in C++ behind ctypes bindings (data/native.py); each has a numpy fallback
// with identical semantics.
//
// All functions are C ABI, operate on caller-allocated buffers, and are
// thread-safe (no global state) so the Python prefetch thread can call them
// without holding locks.

#include <cstdint>
#include <cstring>

extern "C" {

// CLM collation (ref: dataset.py:44-53): batch (B, S+1) token ids ->
// inputs = [:, :-1], labels = [:, 1:] with pad positions masked to -100.
void ftl_collate_clm(const int32_t* batch, int64_t b, int64_t seq_plus1,
                     int32_t pad_id, int32_t* inputs, int32_t* labels) {
  const int64_t s = seq_plus1 - 1;
  for (int64_t i = 0; i < b; ++i) {
    const int32_t* row = batch + i * seq_plus1;
    int32_t* in_row = inputs + i * s;
    int32_t* lb_row = labels + i * s;
    std::memcpy(in_row, row, s * sizeof(int32_t));
    for (int64_t j = 0; j < s; ++j) {
      const int32_t t = row[j + 1];
      lb_row[j] = (t == pad_id) ? -100 : t;
    }
  }
}

// Packed-CLM sample assembly (ref: dataset.py:96-100): a chunk of seq_len+1
// packed tokens -> shifted inputs/labels with BOS positions masked to -100
// on both sides (where input == BOS or label == BOS).
void ftl_pack_clm(const int32_t* chunk, int64_t seq_plus1, int32_t bos_id,
                  int32_t* inputs, int32_t* labels) {
  const int64_t s = seq_plus1 - 1;
  for (int64_t j = 0; j < s; ++j) {
    const int32_t in = chunk[j];
    const int32_t lb = chunk[j + 1];
    inputs[j] = in;
    labels[j] = (in == bos_id || lb == bos_id) ? -100 : lb;
  }
}

// Byte-level tokenization (data/tokenizer.py ByteTokenizer): UTF-8 bytes
// shifted by `offset`, optionally prefixed with BOS. Returns the number of
// ids written (n + (bos_id >= 0)).
int64_t ftl_byte_tokenize(const uint8_t* text, int64_t n, int32_t bos_id,
                          int32_t offset, int32_t* out) {
  int64_t w = 0;
  if (bos_id >= 0) out[w++] = bos_id;
  for (int64_t i = 0; i < n; ++i) out[w++] = offset + (int32_t)text[i];
  return w;
}

}  // extern "C"
